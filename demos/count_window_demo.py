"""Count-measure tumbling window (every 1000 tuples) — the
FlinkSumCountWindowDemo pipeline (demo/flink-demo combined listing :130-153)."""

from data_generator import keyed_stream

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.connectors import KeyedScottyWindowOperator, run_keyed


def main():
    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(WindowMeasure.Count, 1000))
          .add_aggregation(SumAggregation())
          .with_allowed_lateness(1000))
    for key, window in run_keyed(keyed_stream(n=20_000, n_keys=2), op):
        print(f"{key}: {window!r}")


if __name__ == "__main__":
    main()
