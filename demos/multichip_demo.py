"""Multi-chip demo: keyed slice buffers sharded over a device mesh + a
global-window cross-shard combine — the TPU-native replacement for the
reference's host-engine key partitioning (SURVEY.md §2.8). Runs anywhere via
a virtual 8-device CPU mesh."""

import os

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.parallel import (GlobalTpuWindowOperator,
                                     KeyedTpuWindowOperator, make_mesh)

    print("devices:", jax.devices())
    mesh = make_mesh("keys")
    cfg = EngineConfig(capacity=1 << 10, batch_size=256, annex_capacity=128)

    n_keys = 16
    op = KeyedTpuWindowOperator(n_keys=n_keys, config=cfg, mesh=mesh)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    op.add_aggregation(SumAggregation())

    rng = np.random.default_rng(0)
    N = 4096
    keys = rng.integers(0, n_keys, size=N)
    ts = np.sort(rng.integers(0, 10_000, size=N))
    vals = np.ones(N)
    op.process_keyed_elements(keys, vals, ts)
    results = op.process_watermark(10_001)
    print(f"keyed: {len(results)} non-empty windows over {n_keys} key shards")

    gop = GlobalTpuWindowOperator(n_shards=8, config=cfg,
                                  mesh=make_mesh("shards"))
    gop.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    gop.add_aggregation(SumAggregation())
    gop.process_elements(vals, ts)
    for w in gop.process_watermark(10_001):
        if w.has_value():
            print("global:", w)


if __name__ == "__main__":
    main()
