"""Multi-chip demo: keyed slice buffers sharded over a device mesh + a
global-window cross-shard combine — the TPU-native replacement for the
reference's host-engine key partitioning (SURVEY.md §2.8) — plus the
ISSUE 10 mesh engine (shard_map execution, hot-key detection, a
rebalance at a checkpoint boundary) and the ISSUE 13 multi-tenant mesh
service: queries registered MID-STREAM against the sharded step with
zero retraces, answered per key and globally, then a live 8→4 reshard.
Runs anywhere via a virtual 8-device CPU mesh."""

import os

if "XLA_FLAGS" not in os.environ:
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np


def main():
    import jax

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.parallel import (GlobalTpuWindowOperator,
                                     KeyedTpuWindowOperator, make_mesh)

    print("devices:", jax.devices())
    mesh = make_mesh("keys")
    cfg = EngineConfig(capacity=1 << 10, batch_size=256, annex_capacity=128)

    n_keys = 16
    op = KeyedTpuWindowOperator(n_keys=n_keys, config=cfg, mesh=mesh)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    op.add_aggregation(SumAggregation())

    rng = np.random.default_rng(0)
    N = 4096
    keys = rng.integers(0, n_keys, size=N)
    ts = np.sort(rng.integers(0, 10_000, size=N))
    vals = np.ones(N)
    op.process_keyed_elements(keys, vals, ts)
    results = op.process_watermark(10_001)
    print(f"keyed: {len(results)} non-empty windows over {n_keys} key shards")

    gop = GlobalTpuWindowOperator(n_shards=8, config=cfg,
                                  mesh=make_mesh("shards"))
    gop.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    gop.add_aggregation(SumAggregation())
    gop.process_elements(vals, ts)
    for w in gop.process_watermark(10_001):
        if w.has_value():
            print("global:", w)

    # -- ISSUE 10: the mesh engine — shard_map, hot keys, rebalance --------
    import tempfile

    from scotty_tpu.mesh import MeshKeyedEngine
    from scotty_tpu.resilience.supervisor import Supervisor

    eng = MeshKeyedEngine(n_keys=n_keys, n_shards=8, config=cfg)
    eng.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    eng.add_aggregation(SumAggregation())
    hot_keys = keys.copy()
    # plant TWO hot keys that land on the SAME shard (rows 2 and 3):
    # splitting them across shards is exactly what a rebalance can fix
    hot_keys[: N // 4] = 2
    hot_keys[N // 4: N // 2] = 3
    eng.process_keyed_elements(hot_keys, vals, ts)
    results = eng.process_watermark(10_001)
    print(f"mesh: {len(results)} windows over {eng.n_shards} shards, "
          f"occupancy {eng.shard_occupancy().round(3).tolist()}")
    cnt, totals = eng.query_global([0], [10_000])
    print(f"mesh global (in-executable psum): count={int(cnt[0])} "
          f"sum={float(totals[0][0]):.0f}")
    sup = Supervisor(tempfile.mkdtemp(prefix="mesh-demo-"))
    stats = eng.checkpoint_and_rebalance(sup, pos=1)
    print(f"rebalance at checkpoint boundary: moved={stats['moved']} "
          f"imbalance {stats['imbalance_before']:.2f} -> "
          f"{stats['imbalance_after']:.2f}")

    # -- ISSUE 13: one multi-tenant service — register queries mid-stream,
    # answer them per key AND globally, then reshard the mesh live ------
    from scotty_tpu import SlidingWindow
    from scotty_tpu.mesh_serving import MeshQueryService
    from scotty_tpu.serving import QueryAdmission

    svc = MeshQueryService(
        [SumAggregation()], slice_grid=500, max_window_size=4000,
        n_keys=64, n_shards=8, throughput=64_000, wm_period_ms=1000,
        max_lateness=1000, seed=7, config=cfg,
        admission=QueryAdmission(max_queries=16, per_tenant_quota=8,
                                 per_shard_quota=8),
        windows=[TumblingWindow(WindowMeasure.Time, 1000)])
    svc.run(2, collect=False)         # stream flows before the query
    svc.sync()
    svc.mark_warm()
    h = svc.register(SlidingWindow(WindowMeasure.Time, 2000, 500),
                     tenant="acme")   # MID-STREAM: one replicated row
    out = svc.run(1)[0]               # write, zero retraces
    g = svc.global_rows_by_slot(out).get(h.slot, [])
    k = svc.key_rows_by_slot(out, 5).get(h.slot, [])
    print(f"mesh-serving: tenant acme (home shard "
          f"{svc.tenant_shard('acme')}) sees {len(g)} global + "
          f"{len(k)} key-5 windows, retraces_since_warm="
          f"{svc.retraces_since_warm}")
    row = svc.reshard(4, sup, pos=svc.interval)
    out = svc.run(1)[0]
    print(f"live reshard {row['from']}->{row['to']} in "
          f"{row['wall_ms']:.0f} ms; query still answering "
          f"{len(svc.global_rows_by_slot(out).get(h.slot, []))} global "
          f"windows at {svc.n_shards} shards")


if __name__ == "__main__":
    main()
