"""Quantile windows two ways — exact host quantile (the reference's
QuantileTreeMap holistic aggregate, demo/flink-demo/.../QuantileWindowFunction.java:98-135)
and the fixed-width DDSketch device realization (SURVEY.md §7's
capability-preserving substitute)."""

from data_generator import value_stream

from scotty_tpu import (DDSketchQuantileAggregation, QuantileAggregation,
                        SlicingWindowOperator, TumblingWindow, WindowMeasure)
from scotty_tpu.engine import TpuWindowOperator


def main():
    host = SlicingWindowOperator()
    host.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    host.add_aggregation(QuantileAggregation(0.5))

    dev = TpuWindowOperator()
    dev.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    dev.add_aggregation(DDSketchQuantileAggregation(0.5))

    stream = list(value_stream(n=20_000, ms_per_tuple=0.5))
    for v, t in stream:
        host.process_element(v, t)
    dev.process_elements([v for v, _ in stream], [t for _, t in stream])

    wm = stream[-1][1] + 1
    for hw, dw in zip(host.process_watermark(wm), dev.process_watermark(wm)):
        if hw.has_value():
            print(f"[{hw.get_start()},{hw.get_end()}) exact-median="
                  f"{hw.get_agg_values()[0]} ddsketch-median="
                  f"{dw.get_agg_values()[0]:.2f}")


if __name__ == "__main__":
    main()
