"""Dynamic window registration mid-stream, on the device engine.

The reference supports adding window assigners while the stream is running
(TumblingWindowOperatorTest.java:96-145); here the engine rebuilds its
kernels around the new union grid at the registration call while the slice
buffer carries over untouched. Windows of the new assigner that straddle
pre-addition (coarser) slices follow the reference's t_last containment.

Run: PYTHONPATH=. python demos/dynamic_windows_demo.py
"""

import numpy as np

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.engine import EngineConfig, TpuWindowOperator

Time = WindowMeasure.Time


def main() -> None:
    rng = np.random.default_rng(0)
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 12, batch_size=256, annex_capacity=64,
        min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 1000))
    op.add_aggregation(SumAggregation())
    # span the whole demo stream, or the FIRST watermark's lateness clamp
    # (WindowManager.java:43-45) drops the leading windows
    op.set_max_lateness(10_000)

    def feed(lo, hi, n=2048):
        ts = np.sort(rng.integers(lo, hi, size=n)).astype(np.int64)
        vals = np.ones(n, np.float32)
        op.process_elements(vals, ts)

    feed(0, 4000)
    print("watermark 4000 (only the 1 s tumbling window registered):")
    for w in op.process_watermark(4000):
        if w.has_value():
            print(f"  [{w.get_start():5d}, {w.get_end():5d})  "
                  f"count={w.get_agg_values()[0]:.0f}")

    print("\n-- registering a 250 ms tumbling window mid-stream --\n")
    op.add_window_assigner(TumblingWindow(Time, 250))
    feed(4000, 6000)
    print("watermark 6000 (both windows; the fine one starts emitting "
          "from its registration point):")
    for w in op.process_watermark(6000):
        if w.has_value():
            size = w.get_end() - w.get_start()
            print(f"  [{w.get_start():5d}, {w.get_end():5d}) {size:4d}ms  "
                  f"count={w.get_agg_values()[0]:.0f}")


if __name__ == "__main__":
    main()
