"""Keyed sum over tumbling 2 s + sliding 5 s / 1 s windows on a random
source — the FlinkSumDemo pipeline (demo/flink-demo/.../FlinkSumDemo.java:13-39)
on the iterable connector."""

from data_generator import keyed_stream

from scotty_tpu import (SlidingWindow, SumAggregation, TimeMeasure,
                        TumblingWindow, WindowMeasure)
from scotty_tpu.connectors import KeyedScottyWindowOperator, run_keyed


def main():
    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(WindowMeasure.Time,
                                     TimeMeasure.seconds(2).to_milliseconds()))
          .add_window(SlidingWindow(WindowMeasure.Time,
                                    TimeMeasure.seconds(5).to_milliseconds(),
                                    TimeMeasure.seconds(1).to_milliseconds()))
          .add_aggregation(SumAggregation())
          .with_allowed_lateness(100))
    for key, window in run_keyed(keyed_stream(n=20_000, ms_per_tuple=2.0), op):
        print(f"{key}: {window!r}")


if __name__ == "__main__":
    main()
