"""Session windows with gaps in the generator — the reference's session demo
patterns (SessionWindow usage in demo pipelines + benchmark sessionConfig,
BenchmarkRunner.java:174-192)."""

from data_generator import keyed_stream

from scotty_tpu import SessionWindow, SumAggregation, WindowMeasure
from scotty_tpu.connectors import KeyedScottyWindowOperator, run_keyed


def main():
    op = (KeyedScottyWindowOperator()
          .add_window(SessionWindow(WindowMeasure.Time, 500))
          .add_aggregation(SumAggregation())
          .with_allowed_lateness(100))
    src = keyed_stream(n=10_000, n_keys=2, ms_per_tuple=5.0,
                       session_gap_every=500, session_gap_ms=2000)
    for key, window in run_keyed(src, op):
        print(f"{key}: session {window!r}")


if __name__ == "__main__":
    main()
