"""The TPU engine head-on: 60 s sliding window with 100 ms slide (600
concurrent windows) + multi-aggregate, batched device ingest — the pipeline
shape of the reference's headline sliding benchmark
(benchmark/configurations/sliding_benchmark_Scotty.json) as a demo."""

import numpy as np

from scotty_tpu import (MaxAggregation, MeanAggregation, MinAggregation,
                        SlidingWindow, SumAggregation, WindowMeasure)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.utils import ThroughputLogger


def main():
    op = TpuWindowOperator(config=EngineConfig(capacity=1 << 14,
                                               batch_size=1 << 14))
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 60_000, 100))
    for agg in (SumAggregation(), MinAggregation(), MaxAggregation(),
                MeanAggregation()):
        op.add_aggregation(agg)

    rng = np.random.default_rng(0)
    logger = ThroughputLogger(log_every=1 << 18, sink=print)
    n_batches, B = 64, 1 << 14
    ts0 = 0
    for i in range(n_batches):
        span = 2_000                          # 2 event-seconds per batch
        ts = np.sort(rng.integers(ts0, ts0 + span, size=B)).astype(np.int64)
        vals = rng.random(B).astype(np.float32) * 100
        op.process_elements(vals, ts)
        logger.observe(B)
        ts0 += span
        if i % 4 == 3:
            ws, we, cnt, lowered = op.process_watermark_arrays(ts0)
            n = int((cnt > 0).sum())
            print(f"watermark {ts0}: {len(ws)} windows triggered, "
                  f"{n} non-empty, slices={op.n_slices}")


if __name__ == "__main__":
    main()
