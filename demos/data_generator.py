"""Shared demo data generator (the reference's per-engine DemoSource /
DataGeneratorSource / DataGeneratorSpout equivalents, SURVEY.md §2.6):
random keyed tuples with event-time, optional bounded disorder and session
gaps."""

from __future__ import annotations

import numpy as np


def keyed_stream(n: int = 10_000, n_keys: int = 4, seed: int = 0,
                 ms_per_tuple: float = 1.0, disorder_ms: int = 0,
                 session_gap_every: int = 0, session_gap_ms: int = 0):
    """Yield (key, value, ts) tuples with ascending (or boundedly disordered)
    event time."""
    rng = np.random.default_rng(seed)
    ts = 0.0
    for i in range(n):
        ts += rng.exponential(ms_per_tuple)
        if session_gap_every and i and i % session_gap_every == 0:
            ts += session_gap_ms
        t = int(ts)
        if disorder_ms:
            t = max(0, t - int(rng.integers(0, disorder_ms)))
        yield (f"key-{int(rng.integers(0, n_keys))}",
               int(rng.integers(1, 100)), t)


def value_stream(n: int = 10_000, seed: int = 0, ms_per_tuple: float = 1.0):
    for _, v, t in keyed_stream(n, 1, seed, ms_per_tuple):
        yield v, t
