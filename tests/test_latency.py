"""Emission-latency attribution tests (ISSUE 14).

* ManualClock differential suite: per-chain stage sums conserve against
  end-to-end EXACTLY on the injectable clock (advances are exact binary
  floats, so the telescoping identity holds to the bit).
* Sampling-on/off result bit-identity on all four fused pipelines — the
  tracer is host-side only, so window results must be byte-equal with a
  force-sampling tracer attached vs no observability at all.
* Drain-point-only stamping: the traced aligned step runs warm under
  ``jax.transfer_guard("disallow")`` (a stamp that triggered any
  implicit transfer would raise).
* Mesh per-shard fold correctness at the psum drain.
* The operator→sink full-chain walk, the windowed health check naming
  the offending stage, ``obs diff`` failing on an injected first-emit
  regression and on ``latency_stamp_dropped`` appearing, and the
  ``obs latency`` CLI (attribution, conservation exit code, zero-sample
  grace).
"""

import json

import numpy as np
import pytest

from scotty_tpu import obs as _obs
from scotty_tpu.obs import latency as lat
from scotty_tpu.obs.latency import (
    LatencyTracer,
    STAGE_ARRIVAL,
    STAGE_DISPATCH,
    STAGE_DRAIN,
    STAGE_ELIGIBILITY,
    STAGE_EMIT,
    STAGE_RING_DEQUEUE,
    STAGE_RING_ENQUEUE,
    STAGE_SINK,
)
from scotty_tpu.resilience.clock import ManualClock


def make_tracer(**kw):
    obs = _obs.Observability()
    clk = ManualClock()
    kw.setdefault("sample_every", 1)
    kw.setdefault("exact_limit", 1 << 30)
    tr = obs.attach_latency(clock=clk, **kw)
    return obs, clk, tr


# ---------------------------------------------------------------------------
# ManualClock differential suite: conservation
# ---------------------------------------------------------------------------


def test_stage_sums_conserve_exactly():
    obs, clk, tr = make_tracer()
    # exact binary-float advances: the telescoping identity must hold
    # to the BIT, not within a tolerance
    tr.pre(STAGE_ARRIVAL)
    clk.advance(0.25)
    tr.pre(STAGE_RING_ENQUEUE)
    clk.advance(0.5)
    tr.pre(STAGE_RING_DEQUEUE)
    clk.advance(1.0)
    lid = tr.open()
    clk.advance(0.125)
    tr.stamp(lid, STAGE_ELIGIBILITY)
    clk.advance(2.0)
    tr.stamp(lid, STAGE_DRAIN)
    clk.advance(0.25)
    tr.stamp(lid, STAGE_EMIT)
    out = tr.finalize(lid)
    assert sum(out["stages"].values()) == out["end_to_end_ms"]
    assert out["end_to_end_ms"] == (0.25 + 0.5 + 1.0 + 0.125 + 2.0
                                    + 0.25) * 1e3
    # derived numbers: first-emit = eligibility -> first delivery (the
    # drain here precedes emit, so emit is the materialization point —
    # delivery resolution order is sink > emit > drain)
    assert out["first_emit_ms"] == (2.0 + 0.25) * 1e3
    assert out["eligibility_ms"] == out["first_emit_ms"]


def test_conservation_seeded_random_chains():
    obs, clk, tr = make_tracer()
    rng = np.random.default_rng(11)
    for _ in range(50):
        stages = [STAGE_ARRIVAL, STAGE_RING_ENQUEUE, STAGE_RING_DEQUEUE]
        for s in stages:
            if rng.random() < 0.7:
                tr.pre(s)
                # exact binary fractions keep float addition exact
                clk.advance(int(rng.integers(1, 64)) / 64.0)
        lid = tr.open()
        for s in (STAGE_ELIGIBILITY, STAGE_DRAIN, STAGE_EMIT):
            clk.advance(int(rng.integers(1, 64)) / 64.0)
            tr.stamp(lid, s)
        out = tr.finalize(lid)
        assert sum(out["stages"].values()) == out["end_to_end_ms"]
    # the aggregated histogram-level check agrees
    from scotty_tpu.obs.latency import attribute

    attr = attribute(obs.snapshot())
    assert attr["samples"] == 50
    assert attr["conservation_ok"], attr["conservation_gap_ms"]


def test_out_of_order_stamps_sort_by_time():
    # a drain inside the watermark dispatch can pre-stamp AFTER the
    # eligibility moment was captured — finalize orders by time, so no
    # stage duration can ever be negative
    obs, clk, tr = make_tracer()
    lid = tr.open()
    clk.advance(0.5)
    t_later = clk.now()
    clk.advance(0.5)
    tr.stamp(lid, STAGE_DRAIN)
    tr.stamp(lid, STAGE_ELIGIBILITY, at=t_later)  # stamped late, earlier t
    out = tr.finalize(lid)
    assert all(d >= 0 for d in out["stages"].values())
    assert sum(out["stages"].values()) == out["end_to_end_ms"]


# ---------------------------------------------------------------------------
# sampling + bookkeeping
# ---------------------------------------------------------------------------


def test_sampling_one_in_n_with_exact_mode():
    obs, clk, tr = make_tracer(sample_every=4, exact_limit=8)
    keys = [tr.open() for _ in range(32)]
    sampled = [k for k in keys if k is not None]
    # first 8 exact, then every 4th (indices 8, 12, ..., 28)
    assert len(sampled) == 8 + 6
    for k in sampled:
        tr.finalize(k)
    assert tr.dropped == 0


def test_sampling_off_never_opens():
    obs, clk, tr = make_tracer(sample_every=0)
    assert all(tr.open() is None for _ in range(16))
    assert tr.open(force=True) is not None     # probes still force-sample


def test_saturation_declines_instead_of_dropping():
    obs, clk, tr = make_tracer(max_open=4)
    keys = [tr.open() for _ in range(8)]
    assert sum(1 for k in keys if k is not None) == 4
    assert tr.saturated == 4
    assert tr.dropped == 0                     # declines are not drops
    tr.stamp_open(STAGE_DRAIN)
    tr.finalize_open()
    obs_snap = obs.snapshot()
    assert "latency_stamp_dropped" not in obs_snap
    # ...but the coverage loss is exported, not silent
    assert obs_snap["latency_open_declined"] == 4


def test_late_stamp_after_finalize_is_counted_never_raises():
    obs, clk, tr = make_tracer()
    lid = tr.open()
    tr.finalize(lid)
    tr.stamp(lid, STAGE_DRAIN)                 # chain already closed
    tr.finalize(lid)                           # double finalize
    tr.flush()
    assert tr.dropped == 2
    assert obs.snapshot()["latency_stamp_dropped"] == 2


def test_spans_and_flight_events_land():
    flight = _obs.FlightRecorder(capacity=64, clock=ManualClock())
    obs = _obs.Observability(flight=flight)
    clk = ManualClock()
    tr = obs.attach_latency(clock=clk, sample_every=1,
                            exact_limit=1 << 30)
    lid = tr.open()
    clk.advance(0.5)
    tr.stamp(lid, STAGE_DRAIN)
    tr.finalize(lid)
    names = {s.name for s in obs.spans.spans}
    assert "latency/drain" in names
    kinds = [(e["kind"], e["name"]) for e in flight.events()]
    assert ("latency_stage", "drain") in kinds


# ---------------------------------------------------------------------------
# operator → sink full chain (ManualClock)
# ---------------------------------------------------------------------------


def test_operator_sink_full_chain():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
    from scotty_tpu.delivery import TransactionalSink
    from scotty_tpu.engine import EngineConfig, TpuWindowOperator

    obs = _obs.Observability()
    clk = ManualClock()
    tr = obs.attach_latency(clock=clk, sample_every=1,
                            exact_limit=1 << 30)
    op = TpuWindowOperator(config=EngineConfig(capacity=128,
                                               annex_capacity=16,
                                               batch_size=8), obs=obs)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 100))
    op.add_aggregation(SumAggregation())
    # first-watermark trigger range starts at wm - max_lateness: a
    # lateness covering the stream makes the single watermark emit
    # every closed window
    op.set_max_lateness(1000)
    delivered = []
    sink = TransactionalSink(deliver=lambda w, e, s: delivered.append(w),
                             obs=obs)

    chains = []
    orig = tr._finalize
    tr._finalize = lambda c: chains.append(orig(c)) or chains[-1]

    vals = np.arange(16, dtype=np.float32)
    ts = np.arange(16, dtype=np.int64) * 20          # 0..300
    clk.advance(0.25)
    op.process_elements(vals, ts)
    clk.advance(0.25)
    out = op.process_watermark(301)
    for w in out:
        if w.has_value():
            clk.advance(0.125)
            sink.emit(w)
    op.check_overflow()                              # folds parked chain

    assert len(delivered) >= 3
    assert len(chains) == 1
    c = chains[0]
    # the full walk: arrival pre-stamp, dispatch pre-stamp, eligibility,
    # drain at the fetch, emit at materialization, sink at the handoff
    for s in (STAGE_ARRIVAL, STAGE_DISPATCH, STAGE_ELIGIBILITY,
              STAGE_DRAIN, STAGE_EMIT, STAGE_SINK):
        assert s in c["stamps"], (s, sorted(c["stamps"]))
    assert sum(c["stages"].values()) == c["end_to_end_ms"]
    # first-emit: eligibility -> FIRST sink delivery (one 0.125 s
    # advance past emit); eligibility lag reaches the LAST delivery
    assert c["first_emit_ms"] == pytest.approx(
        c["stamps"][STAGE_SINK] * 1e3
        - c["stamps"][STAGE_ELIGIBILITY] * 1e3)
    assert c["eligibility_ms"] >= c["first_emit_ms"]
    n = len(delivered)
    assert c["eligibility_ms"] - c["first_emit_ms"] == pytest.approx(
        (n - 1) * 125.0)
    snap = obs.snapshot()
    assert snap["latency_lineages"] == 1
    assert snap["latency_first_emit_ms_count"] == 1
    assert "latency_stamp_dropped" not in snap


# ---------------------------------------------------------------------------
# fused pipelines: bit-identity + drain-point-only stamping
# ---------------------------------------------------------------------------


def _pipeline_results(p, n=6):
    import jax

    p.reset()
    outs = p.run(n, collect=True)
    p.sync()
    fetched = jax.device_get([(o[2], o[3]) for o in outs])
    p.check_overflow()
    return fetched


def _assert_bit_identical(mk):
    a = _pipeline_results(mk())
    p = mk()
    obs = _obs.Observability()
    obs.attach_latency(sample_every=1, exact_limit=1 << 30)
    p.set_observability(obs)
    b = _pipeline_results(p)
    for (ca, ra), (cb, rb) in zip(a, b):
        np.testing.assert_array_equal(ca, cb)
        for x, y in zip(ra, rb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    snap = obs.snapshot()
    assert snap.get("latency_lineages", 0) > 0
    assert "latency_stamp_dropped" not in snap


CFG = None


def _cfg():
    global CFG
    if CFG is None:
        from scotty_tpu.engine import EngineConfig

        CFG = EngineConfig(capacity=512, annex_capacity=8,
                           min_trigger_pad=32)
    return CFG


def test_bit_identity_aligned():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import SlidingWindow, WindowMeasure
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    _assert_bit_identical(lambda: AlignedStreamPipeline(
        [SlidingWindow(WindowMeasure.Time, 2000, 1000)],
        [SumAggregation()], config=_cfg(), throughput=8000,
        wm_period_ms=1000, max_lateness=0, seed=3, gc_every=32))


def test_bit_identity_stream():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import FixedBandWindow, WindowMeasure
    from scotty_tpu.engine.pipeline import StreamPipeline

    _assert_bit_identical(lambda: StreamPipeline(
        [FixedBandWindow(WindowMeasure.Time, 500, 2500)],
        [SumAggregation()], config=_cfg(), throughput=8000,
        wm_period_ms=1000, max_lateness=0, seed=3))


def test_bit_identity_count():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    _assert_bit_identical(lambda: CountStreamPipeline(
        [TumblingWindow(WindowMeasure.Count, 1000)],
        [SumAggregation()], config=_cfg(), throughput=8000,
        wm_period_ms=1000, max_lateness=1000, seed=3))


def test_bit_identity_session():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import SessionWindow, WindowMeasure
    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    _assert_bit_identical(lambda: SessionStreamPipeline(
        [SessionWindow(WindowMeasure.Time, 150)],
        [SumAggregation()], config=_cfg(), throughput=2000,
        wm_period_ms=1000, max_lateness=0, seed=3,
        session_config={"silence_pct": 20}))


def test_traced_aligned_step_under_transfer_guard():
    """Drain-point-only stamping: a warm traced step loop must not
    introduce any implicit host<->device transfer."""
    import jax

    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import SlidingWindow, WindowMeasure
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [SlidingWindow(WindowMeasure.Time, 2000, 1000)],
        [SumAggregation()], config=_cfg(), throughput=8000,
        wm_period_ms=1000, max_lateness=0, seed=3, gc_every=32)
    obs = _obs.Observability()
    obs.attach_latency(sample_every=1, exact_limit=1 << 30)
    p.reset()
    p.run(2, collect=False)                     # warm compile
    p.sync()
    p.set_observability(obs)
    with jax.transfer_guard("disallow"):
        p.run(3, collect=False)
    p.sync()
    p.check_overflow()
    assert obs.snapshot().get("latency_lineages", 0) > 0


# ---------------------------------------------------------------------------
# mesh per-shard fold at the psum drain
# ---------------------------------------------------------------------------


def test_mesh_per_shard_fold():
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.mesh.pipeline import MeshKeyedPipeline

    n_keys, n_shards = 16, 8
    p = MeshKeyedPipeline(
        [TumblingWindow(WindowMeasure.Time, 100)], [SumAggregation()],
        n_keys=n_keys, n_shards=n_shards,
        config=EngineConfig(capacity=128, annex_capacity=16),
        throughput=n_keys * 2000, wm_period_ms=1000, seed=5)
    obs = _obs.Observability()
    tr = obs.attach_latency(clock=ManualClock(), sample_every=1,
                            exact_limit=1 << 30)
    p.set_observability(obs)
    outs = p.run(2, collect=True)
    p.sync()
    sampled = [0, 3, 7, 8, 15]
    for k in sampled:
        p.lowered_results_for_key(outs[-1], k)
    p.check_overflow()
    snap = obs.snapshot()
    counts = {s: snap.get(f"latency_shard_{s}_emit_ms_count", 0)
              for s in range(n_shards)}
    # fold correctness: every sampled key's fetch landed on its OWNING
    # shard (row_of // rows_per_shard), nothing else counted
    expect = {}
    for k in sampled:
        s = int(p.routing.row_of[k]) // p.routing.rows_per_shard
        expect[s] = expect.get(s, 0) + 1
    assert sum(counts.values()) == len(sampled)
    for s in range(n_shards):
        assert counts[s] == expect.get(s, 0), (s, counts, expect)
    # the driver chains rode the same run: sampled and conserving
    assert snap.get("latency_lineages", 0) >= 2
    assert "latency_stamp_dropped" not in snap


# ---------------------------------------------------------------------------
# health policy: windowed first-emit verdict names the owning stage
# ---------------------------------------------------------------------------


def test_health_first_emit_names_offending_stage():
    from scotty_tpu.obs.server import HealthPolicy

    obs, clk, tr = make_tracer()
    for _ in range(8):
        lid = tr.open()
        clk.advance(0.005)
        tr.stamp(lid, STAGE_ELIGIBILITY)
        clk.advance(0.200)                       # drain owns the path
        tr.stamp(lid, STAGE_DRAIN)
        clk.advance(0.001)
        tr.stamp(lid, STAGE_EMIT)
        tr.finalize(lid)
    policy = HealthPolicy(max_first_emit_p99_ms=50.0,
                          stall_unhealthy=False,
                          overflow_unhealthy=False)
    v = policy.verdict(obs)
    assert not v["healthy"]
    fe = v["checks"]["first_emit"]
    assert fe["ok"] is False
    assert fe["p99_ms"] > 50.0
    assert fe["owning_stage"] == "drain"
    # raising the bound recovers
    ok = HealthPolicy(max_first_emit_p99_ms=10_000.0,
                      stall_unhealthy=False,
                      overflow_unhealthy=False).verdict(obs)
    assert ok["healthy"]


def test_health_first_emit_graceful_without_samples():
    from scotty_tpu.obs.server import HealthPolicy

    obs = _obs.Observability()                   # no tracer at all
    policy = HealthPolicy(max_first_emit_p99_ms=1.0,
                          stall_unhealthy=False,
                          overflow_unhealthy=False)
    v = policy.verdict(obs)
    assert v["healthy"]
    assert v["checks"]["first_emit"]["samples"] == 0


# ---------------------------------------------------------------------------
# obs diff: injected latency regression gates
# ---------------------------------------------------------------------------


def _snap_export(tmp_path, name, p99, dropped=None):
    row = {"latency_first_emit_ms_p99": p99,
           "latency_first_emit_ms_count": 20,
           "tuples_per_sec": 1_000_000.0}
    if dropped is not None:
        row["latency_stamp_dropped"] = dropped
    path = tmp_path / name
    path.write_text(json.dumps(row))
    return str(path)


def test_diff_gates_injected_first_emit_regression(tmp_path):
    from scotty_tpu.obs.diff import diff_main

    base = _snap_export(tmp_path, "base.json", 70.0)
    ok = _snap_export(tmp_path, "ok.json", 74.0)       # +5.7% < 10%
    bad = _snap_export(tmp_path, "bad.json", 95.0)     # +35%
    out = []
    assert diff_main(base, ok, echo=out.append) == 0
    assert diff_main(base, bad, echo=out.append) == 1
    # the table truncates metric names to 22 chars — match the prefix
    assert any("latency_first_emit_ms" in line
               for line in out if "REGRESSED" in line.upper())


def test_diff_gates_stamp_dropped_appearing(tmp_path):
    from scotty_tpu.obs.diff import diff_main

    base = _snap_export(tmp_path, "base.json", 70.0)
    cand = _snap_export(tmp_path, "cand.json", 70.0, dropped=3)
    assert diff_main(base, cand, echo=lambda s: None) == 1


def test_diff_first_emit_cell_field_gates(tmp_path):
    from scotty_tpu.obs.diff import diff_main

    def cell(path, p99):
        rows = [{"name": "c", "windows": "w", "engine": "e",
                 "aggregation": "sum", "tuples_per_sec": 1e6,
                 "first_emit_p99_ms": p99, "first_emit_samples": 10}]
        path.write_text(json.dumps(rows))
        return str(path)

    base = cell(tmp_path / "b.json", 70.0)
    bad = cell(tmp_path / "c.json", 90.0)
    assert diff_main(base, bad, echo=lambda s: None) == 1


# ---------------------------------------------------------------------------
# CLI + report
# ---------------------------------------------------------------------------


def _traced_snapshot_file(tmp_path):
    obs, clk, tr = make_tracer()
    for _ in range(4):
        tr.pre(STAGE_ARRIVAL)
        clk.advance(0.25)
        lid = tr.open()
        clk.advance(0.125)
        tr.stamp(lid, STAGE_ELIGIBILITY)
        clk.advance(1.0)
        tr.stamp(lid, STAGE_DRAIN)
        clk.advance(0.0625)
        tr.stamp(lid, STAGE_EMIT)
        tr.finalize(lid)
    path = tmp_path / "snap.json"
    path.write_text(json.dumps(obs.snapshot(), default=float))
    return str(path)


def test_latency_cli_attributes_and_exits_zero(tmp_path, capsys):
    from scotty_tpu.obs.report import main

    path = _traced_snapshot_file(tmp_path)
    assert main(["latency", path]) == 0
    out = capsys.readouterr().out
    assert "owns p99" in out
    assert "drain" in out
    assert "conservation" in out and "ok" in out


def test_latency_cli_conservation_violation_exits_nonzero(tmp_path):
    from scotty_tpu.obs.report import main

    # forge an export whose stage sums cannot match end-to-end
    row = {"latency_end_to_end_ms_count": 10,
           "latency_end_to_end_ms_mean": 100.0,
           "latency_stage_drain_ms_count": 10,
           "latency_stage_drain_ms_mean": 10.0,
           "latency_stage_drain_ms_p50": 10.0,
           "latency_stage_drain_ms_p99": 10.0}
    path = tmp_path / "bad.json"
    path.write_text(json.dumps(row))
    assert main(["latency", str(path)]) == 1


def test_latency_cli_zero_samples_graceful(tmp_path, capsys):
    from scotty_tpu.obs.report import main

    path = tmp_path / "empty.json"
    path.write_text(json.dumps({"tuples_per_sec": 1.0}))
    assert main(["latency", str(path)]) == 0
    assert "no latency samples" in capsys.readouterr().out


def test_report_latency_section_zero_samples_never_crashes(tmp_path,
                                                           capsys):
    from scotty_tpu.obs.report import main

    rows = [{"name": "c", "windows": "w", "engine": "e",
             "aggregation": "sum", "tuples_per_sec": 1e6,
             "metrics": {"metrics": {"ingest_tuples": 5.0}}}]
    path = tmp_path / "res.json"
    path.write_text(json.dumps(rows))
    assert main(["report", str(path)]) == 0
    assert "no latency samples" in capsys.readouterr().out


def test_report_latency_section_with_samples(tmp_path, capsys):
    from scotty_tpu.obs.report import main

    path = _traced_snapshot_file(tmp_path)
    assert main(["report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "latency:" in out and "p99 owner" in out


# ---------------------------------------------------------------------------
# lint coverage: the no-wall-clock rule covers obs/latency.py
# ---------------------------------------------------------------------------


def test_no_wall_clock_rule_covers_latency_module():
    from scotty_tpu.analysis.rules.hygiene import NoWallClock

    assert any("scotty_tpu/obs" == inc or inc == "scotty_tpu"
               for inc in NoWallClock.include)
    # and the module really routes through the injectable clock
    import inspect

    src = inspect.getsource(lat)
    assert "time.time(" not in src and "time.monotonic(" not in src
    assert "resilience.clock" in src or "from ..resilience.clock" in src
