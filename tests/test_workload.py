"""ISSUE 16 sensor plane: workload fingerprints sampled at drain
points, drift detection with bounded detect lag and zero false
positives on stable streams, the per-stage cost model fit from the
checked-in bench corpus, and the obs drift/trend/costmodel CLIs."""

import json
import os

import numpy as np
import pytest

from scotty_tpu.obs import (
    COSTMODEL_RESIDUAL_PCT,
    RESIDUAL_BOUND_PCT,
    WORKLOAD_AUDITS,
    WORKLOAD_DRIFT_EVENTS,
    CostModel,
    DriftDetector,
    HealthPolicy,
    Observability,
    WorkloadFingerprint,
    WorkloadMonitor,
    feature_gauge,
)
from scotty_tpu.obs import costmodel as cm
from scotty_tpu.obs.device import LATE_AGE_EDGES_MS, late_bucket_names
from scotty_tpu.obs.diff import _cells
from scotty_tpu.obs.drift import (
    DEFAULT_DRIFT_THRESHOLDS,
    compare_features,
    load_fingerprint,
)
from scotty_tpu.obs.report import main as obs_main
from scotty_tpu.obs.trend import build_trend
from scotty_tpu.obs.workload import _late_age_p50
from scotty_tpu.resilience.clock import ManualClock

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
RESULTS = os.path.join(REPO, "bench_results")


# ---------------------------------------------------------------------------
# monitor plumbing
# ---------------------------------------------------------------------------


def _mk_monitor(**kw):
    obs = Observability()
    clock = ManualClock()
    mon = obs.attach_workload(
        WorkloadMonitor(clock=clock, audit_interval_s=1.0, **kw))
    return obs, clock, mon


def _second(obs, clock, mon, n_in=1000, n_late=0, key_loads=None,
            late_buckets=None):
    """Simulate one second of stream telemetry then hit the drain point."""
    obs.counter("ingest_tuples").inc(n_in)
    if n_late:
        obs.counter("late_tuples").inc(n_late)
    for name, c in (late_buckets or {}).items():
        obs.counter(name).inc(c)
    if key_loads is not None:
        mon.observe_key_loads(key_loads)
    clock.advance(1.0)
    obs.flight_sync()


def test_monitor_arms_then_audits_per_window():
    obs, clock, mon = _mk_monitor()
    _second(obs, clock, mon, n_in=500)          # arms the first window
    assert mon.audits == 0
    _second(obs, clock, mon, n_in=1000)
    assert mon.audits == 1
    feats = mon.features()
    assert feats["arrival_rate_per_s"] == pytest.approx(1000.0)
    assert feats["late_share"] == 0.0
    # features double as workload_<feature> gauges + the audit counter
    reg = obs.registry
    assert reg.gauges[feature_gauge("arrival_rate_per_s")].value \
        == pytest.approx(1000.0)
    assert reg.counters[WORKLOAD_AUDITS].value == 1.0


def test_sub_interval_samples_are_cheap_no_audit():
    obs, clock, mon = _mk_monitor()
    _second(obs, clock, mon)                    # arm
    obs.counter("ingest_tuples").inc(100)
    clock.advance(0.25)                         # inside the audit window
    obs.flight_sync()
    assert mon.audits == 0                      # clock read only, no fold


def test_flight_sync_samples_without_flight_recorder():
    # the workload sample must run even with NO flight ring attached —
    # flight_sync is the drain-point hook, not a flight-only path
    obs, clock, mon = _mk_monitor()
    assert obs.flight is None
    _second(obs, clock, mon)
    _second(obs, clock, mon)
    assert mon.audits == 1


def test_fingerprint_in_export_and_roundtrip():
    obs, clock, mon = _mk_monitor()
    _second(obs, clock, mon)
    _second(obs, clock, mon, n_in=2000)
    out = obs.export()
    fp = out["fingerprint"]
    assert fp["schema"] == "scotty_tpu.workload/1"
    assert fp["audits"] == 1
    assert fp["features"]["arrival_rate_per_s"] == pytest.approx(2000.0)
    rt = WorkloadFingerprint.from_dict(json.loads(json.dumps(fp)))
    assert rt.features == pytest.approx(fp["features"])
    # flat-gauge fallback reconstruction (exports without the section)
    flat = {feature_gauge("arrival_rate_per_s"): 2000.0,
            feature_gauge("late_share"): 0.25, WORKLOAD_AUDITS: 7}
    fp2 = WorkloadFingerprint.from_flat_metrics(flat)
    assert fp2.features == {"arrival_rate_per_s": 2000.0,
                            "late_share": 0.25}
    assert fp2.audits == 7


def test_late_age_p50_walks_the_strata():
    names = late_bucket_names()
    # all mass in the first bucket -> its upper edge
    assert _late_age_p50({names[0]: 10.0}) == float(LATE_AGE_EDGES_MS[0])
    # median lands in the second bucket
    assert _late_age_p50({names[0]: 2.0, names[1]: 8.0}) \
        == float(LATE_AGE_EDGES_MS[1])
    # all mass overflow -> the conservative 2x last edge
    assert _late_age_p50({names[-1]: 5.0}) \
        == float(2 * LATE_AGE_EDGES_MS[-1])
    assert _late_age_p50({}) == 0.0


def test_monitor_folds_late_age_from_device_strata():
    obs, clock, mon = _mk_monitor()
    names = late_bucket_names()
    _second(obs, clock, mon)
    obs.counter("device_ingest_tuples").inc(1000)
    obs.counter("device_late_tuples").inc(100)
    _second(obs, clock, mon, n_in=0,
            late_buckets={names[2]: 60, names[0]: 40})
    feats = mon.features()
    assert feats["late_share"] == pytest.approx(0.1)
    assert feats["late_age_p50_ms"] == float(LATE_AGE_EDGES_MS[2])


def test_key_skew_features_from_load_histogram():
    obs, clock, mon = _mk_monitor(top_k=8)
    _second(obs, clock, mon, key_loads=np.ones(64))
    _second(obs, clock, mon, key_loads=np.ones(64))
    feats = mon.features()
    assert feats["key_top_share"] == pytest.approx(8 / 64)
    assert feats["key_entropy"] == pytest.approx(1.0)
    skew = np.ones(64)
    skew[0] = 64 * 4                           # one key owns ~80%
    _second(obs, clock, mon, key_loads=skew)
    feats = mon.features()
    assert feats["key_top_share"] > 0.8
    assert feats["key_entropy"] < 0.5


# ---------------------------------------------------------------------------
# drift detection: injections + bounded detect lag, zero false positives
# ---------------------------------------------------------------------------


def _with_detector(**det_kw):
    obs, clock, mon = _mk_monitor()
    det = DriftDetector(**det_kw)
    mon.attach_detector(det)
    return obs, clock, mon, det


def test_rate_shift_detected_within_bounded_window():
    obs, clock, mon, det = _with_detector()
    for _ in range(6):                          # arm + baseline + stable
        _second(obs, clock, mon, n_in=1000)
    assert det.events == 0
    shift_audit = mon.audits + 1
    for _ in range(4):
        _second(obs, clock, mon, n_in=8000)
    fired = {f["feature"]: f["audit"] for f in det.fired}
    assert "arrival_rate_per_s" in fired
    # confirm=2 hysteresis: detected within <= 4 audit windows of onset
    assert fired["arrival_rate_per_s"] - shift_audit + 1 <= 4
    assert obs.registry.counters[WORKLOAD_DRIFT_EVENTS].value \
        == float(det.events)


def test_lateness_storm_detected():
    obs, clock, mon, det = _with_detector()
    for _ in range(6):
        _second(obs, clock, mon, n_in=1000)
    assert det.events == 0
    storm_audit = mon.audits + 1
    for _ in range(4):
        _second(obs, clock, mon, n_in=1000, n_late=300)
    fired = {f["feature"]: f["audit"] for f in det.fired}
    assert "late_share" in fired
    assert fired["late_share"] - storm_audit + 1 <= 4


def test_key_skew_flip_detected():
    obs, clock, mon, det = _with_detector()
    uniform = np.ones(64)
    skew = np.ones(64)
    skew[0] = 64 * 4
    for _ in range(6):
        _second(obs, clock, mon, key_loads=uniform)
    assert det.events == 0
    flip_audit = mon.audits + 1
    for _ in range(4):
        _second(obs, clock, mon, key_loads=skew)
    fired = {f["feature"]: f["audit"] for f in det.fired}
    assert "key_top_share" in fired and "key_entropy" in fired
    assert fired["key_top_share"] - flip_audit + 1 <= 4


def test_stable_stream_fires_zero_false_positives():
    obs, clock, mon, det = _with_detector()
    rng = np.random.default_rng(7)
    for _ in range(60):                         # long stable arm, jittered
        n = int(1000 * (1.0 + rng.uniform(-0.05, 0.05)))
        _second(obs, clock, mon, n_in=n, key_loads=np.ones(64))
    assert det.events == 0
    assert WORKLOAD_DRIFT_EVENTS not in obs.registry.counters


def test_drift_latch_fires_once_then_rearms():
    det = DriftDetector(reference={"late_share": 0.0}, confirm=2)
    audits = [0.0] * 4 + [0.4] * 6 + [0.0] * 4 + [0.4] * 3
    fired = []
    for v in audits:
        fired += det.observe({"late_share": v})
    # one event per sustained excursion, re-armed by the in-band gap
    assert fired == ["late_share", "late_share"]
    assert det.events == 2


def test_explicit_reference_judges_immediately():
    ref = WorkloadFingerprint(features={"arrival_rate_per_s": 1000.0})
    det = DriftDetector(reference=ref, confirm=1)
    assert det.observe({"arrival_rate_per_s": 1050.0}) == []
    assert det.observe({"arrival_rate_per_s": 9000.0}) \
        == ["arrival_rate_per_s"]


def test_compare_features_judges_shared_set_only():
    findings = compare_features(
        {"late_share": 0.0, "fill_ratio": 0.9},
        {"late_share": 0.3, "key_entropy": 0.2})
    assert [f["feature"] for f in findings] == ["late_share"]
    assert findings[0]["drifted"]
    for feature in DEFAULT_DRIFT_THRESHOLDS:
        assert set(DEFAULT_DRIFT_THRESHOLDS[feature]) \
            <= {"rel_tol", "abs_tol"}


def test_healthz_drift_check_probes_new_events():
    obs, clock, mon, det = _with_detector()
    policy = HealthPolicy()
    # no drift counter yet -> the check must not appear (runs without a
    # detector keep their exact verdict shape)
    assert "workload_drift" not in policy.verdict(obs)["checks"]
    for _ in range(6):
        _second(obs, clock, mon, n_in=1000)
    for _ in range(4):
        _second(obs, clock, mon, n_in=9000)
    v = policy.verdict(obs)
    chk = v["checks"]["workload_drift"]
    assert chk["new_since_last_probe"] >= 1 and not chk["ok"]
    assert not v["healthy"]
    # next probe with no NEW events: healthy again (edge-triggered)
    v2 = policy.verdict(obs)
    assert v2["checks"]["workload_drift"]["ok"]


def test_keyed_connector_counts_late_tuples():
    from scotty_tpu.connectors.base import (AscendingWatermarks,
                                            KeyedScottyWindowOperator)
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure

    obs = Observability()
    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(WindowMeasure.Time, 100)],
        aggregations=[SumAggregation()], allowed_lateness=500,
        watermark_policy=AscendingWatermarks(), obs=obs)
    for ts in (10, 200, 400, 900):
        op.process_element("k", 1.0, ts)
    assert obs.registry.counters.get("late_tuples") is None or \
        obs.registry.counters["late_tuples"].value == 0.0
    op.process_element("k", 1.0, 450)          # below wm, within lateness
    assert obs.registry.counters["late_tuples"].value == 1.0


# ---------------------------------------------------------------------------
# cost model: synthetic round-trips + the checked-in corpus
# ---------------------------------------------------------------------------


def _flat(rate_mtps, **targets):
    flat = {"tuples_per_sec": rate_mtps * 1e6}
    for target, ms in targets.items():
        flat[f"{target}_mean"] = ms
        flat[f"{target}_count"] = 5
    return flat


def test_costmodel_fit_recovers_affine_law():
    cells = [_flat(r, sync_ms=2.0 + 3.0 * r) for r in (1.0, 2.0, 4.0)]
    model = cm.fit(cells)
    law = model.laws["sync_ms"]
    assert law["fit_residual_pct"] < 0.5
    assert model.predict(8.0)["sync_ms"] == pytest.approx(26.0, rel=1e-6)


def test_costmodel_fit_recovers_reciprocal_law():
    # tuples-per-interval physics: interval_step_ms * rate ~ constant
    cells = [_flat(r, interval_step_ms=1.0 + 240.0 / r)
             for r in (10.0, 20.0, 40.0, 60.0)]
    model = cm.fit(cells)
    law = model.laws["interval_step_ms"]
    assert law["per_inv_mtuple_s"] == pytest.approx(240.0, rel=1e-3)
    assert law["fit_residual_pct"] < 0.5
    # held-out rate round-trips through the reciprocal term
    assert model.predict(30.0)["interval_step_ms"] \
        == pytest.approx(9.0, rel=1e-3)


def test_costmodel_single_rate_degrades_to_intercept():
    cells = [_flat(2.0, sync_ms=7.0), _flat(2.0, sync_ms=9.0)]
    law = cm.fit(cells).laws["sync_ms"]
    assert law["per_mtuple_s"] == 0.0
    assert law["intercept"] == pytest.approx(8.0)


def test_costmodel_live_residual_and_drift_feature():
    model = CostModel(laws={"interval_step_ms": {
        "intercept": 0.0, "per_mtuple_s": 0.0,
        "per_inv_mtuple_s": 2000.0, "n_cells": 4,
        "fit_residual_pct": 0.0}})
    feats = {"arrival_rate_per_s": 50e6}       # 50 Mt/s -> 40 ms predicted
    assert model.predict_interval_ms(feats) == pytest.approx(40.0)
    assert model.residual_pct(feats, 40.0) == pytest.approx(0.0)
    assert model.residual_pct(feats, 60.0) == pytest.approx(50.0)
    assert model.residual_pct(feats, None) is None
    # riding the monitor: residual lands in the gauge + the feature set
    obs, clock, mon = _mk_monitor()
    mon.attach_costmodel(model)
    det = DriftDetector(reference={"arrival_rate_per_s": 50e6,
                                   "costmodel_residual_pct": 0.0},
                        confirm=1)
    mon.attach_detector(det)
    _second(obs, clock, mon)
    obs.counter("ingest_tuples").inc(50_000_000)
    obs.histogram("interval_step_ms").observe(80.0)  # 2x the prediction
    clock.advance(1.0)
    obs.flight_sync()
    assert obs.registry.gauges[COSTMODEL_RESIDUAL_PCT].value \
        == pytest.approx(100.0)
    assert any(f["feature"] == "costmodel_residual_pct"
               for f in det.fired)


def test_costmodel_corpus_leave_one_out_within_bound():
    """The sliding-count family (4 cells, one window shape, 4 rates) is
    the corpus regime the reciprocal law models: each held-out cell's
    interval_step_ms must predict within the stated residual bound."""
    flats = list(_cells(os.path.join(
        RESULTS, "result_sliding-count.json")).values())
    usable = [f for f in flats
              if cm._cell_rate_mtps(f)
              and "interval_step_ms" in cm._cell_observations(f)]
    assert len(usable) >= 4
    for i, held in enumerate(usable):
        model = cm.fit(usable[:i] + usable[i + 1:])
        rate = cm._cell_rate_mtps(held)
        observed = cm._cell_observations(held)["interval_step_ms"]
        predicted = model.predict(rate)["interval_step_ms"]
        residual = 100.0 * abs(predicted - observed) / observed
        assert residual <= RESIDUAL_BOUND_PCT, \
            f"cell {i}: {residual:.1f}% > {RESIDUAL_BOUND_PCT}%"


def test_costmodel_drain_ownership_matches_pr13_attribution():
    """The PR 13 stage-stamped lineage put drain_fetch at 67-71 ms of
    the ~70.8 ms first-emit anchor; the fitted decomposition must
    reproduce that ownership from the checked-in headline cell."""
    path = os.path.join(RESULTS, "result_latency-headline.json")
    (flat,) = _cells(path).values()
    drain_p99 = flat["latency_stage_drain_ms_p99"]
    fe_p99 = flat["latency_first_emit_ms_p99"]
    assert 66.0 <= drain_p99 <= 72.0
    assert drain_p99 >= 0.90 * fe_p99          # drain owns the anchor
    model = cm.fit_paths([path])
    rate = cm._cell_rate_mtps(flat)
    grouped = model.grouped(rate)
    assert grouped["drain_fetch"] == \
        pytest.approx(flat["latency_stage_drain_ms_mean"], rel=1e-6)
    # drain_fetch dominates every other PROCESSING group (generator_lift
    # carries the eligibility stage — event-time slack waiting for the
    # watermark, not work on the 70.8 ms first-emit critical path)
    others = sum(ms for g, ms in grouped.items()
                 if g not in ("drain_fetch", "generator_lift"))
    assert grouped["drain_fetch"] > others


# ---------------------------------------------------------------------------
# CLIs: obs drift / trend / costmodel exit codes
# ---------------------------------------------------------------------------


def _write(path, obj):
    with open(path, "w") as f:
        json.dump(obj, f)
    return str(path)


def test_obs_drift_cli_exit_codes(tmp_path):
    base = _write(tmp_path / "base.json",
                  {"schema": "scotty_tpu.workload/1", "audits": 5,
                   "features": {"arrival_rate_per_s": 1000.0,
                                "late_share": 0.0}})
    same = _write(tmp_path / "same.json",
                  {"schema": "scotty_tpu.workload/1", "audits": 5,
                   "features": {"arrival_rate_per_s": 1040.0,
                                "late_share": 0.0}})
    moved = _write(tmp_path / "moved.json",
                   {"schema": "scotty_tpu.workload/1", "audits": 5,
                    "features": {"arrival_rate_per_s": 9000.0,
                                 "late_share": 0.4}})
    bare = _write(tmp_path / "bare.json", {"not": "a fingerprint"})
    assert obs_main(["drift", base, same]) == 0
    assert obs_main(["drift", base, moved]) == 1
    assert obs_main(["drift", base, bare]) == 2


def test_load_fingerprint_from_recorded_cell():
    fp = load_fingerprint(os.path.join(
        RESULTS, "result_workload-drift.json"))
    assert fp is not None
    assert fp.features["arrival_rate_per_s"] > 0
    assert fp.audits > 0


def test_obs_trend_reconstructs_rounds_and_exit_codes(tmp_path):
    paths = sorted(
        os.path.join(REPO, f) for f in os.listdir(REPO)
        if f.startswith("BENCH_r") and f.endswith(".json"))
    assert len(paths) >= 5
    trend = build_trend(paths=paths, results_dir=RESULTS)
    assert [r["round"] for r in trend["rounds"]] \
        == sorted(r["round"] for r in trend["rounds"])
    assert len(trend["rounds"]) >= 5
    assert trend["transitions"], "no judged transitions"
    # the checked-in trajectory is clean under the obs-diff thresholds
    assert all(t["status"] == "ok" for t in trend["transitions"])
    assert obs_main(["trend", *paths, "--results", RESULTS]) == 0
    # a synthetic regressed round must flag + exit 1
    r1 = _write(tmp_path / "BENCH_r90.json",
                {"n": 90, "parsed": {"metric": "tuples_per_sec",
                                     "value": 1_000_000.0,
                                     "p99_window_emit_ms": 10.0}})
    r2 = _write(tmp_path / "BENCH_r91.json",
                {"n": 91, "parsed": {"metric": "tuples_per_sec",
                                     "value": 400_000.0,
                                     "p99_window_emit_ms": 40.0}})
    assert obs_main(["trend", r1, r2]) == 1
    # no parseable rounds
    junk = _write(tmp_path / "BENCH_r99.json", {"no": "parsed"})
    assert obs_main(["trend", junk]) == 2


def test_obs_costmodel_cli_fit_predict_exit_codes(tmp_path):
    corpus = os.path.join(RESULTS, "result_sliding-count.json")
    model_path = str(tmp_path / "model.json")
    assert obs_main(["costmodel", "fit", corpus, "-o", model_path]) == 0
    model = CostModel.load(model_path)
    assert model.schema == cm.COSTMODEL_SCHEMA
    assert "interval_step_ms" in model.laws
    # predicting the fit corpus stays within the stated bound
    assert obs_main(["costmodel", "predict", model_path, corpus]) == 0
    # a cell far outside the fitted regime blows the headline residual
    blown = _write(tmp_path / "blown.json",
                   [{"name": "x", "windows": "w", "engine": "e",
                     "aggregation": "sum", "tuples_per_sec": 50e6,
                     "metrics": {"metrics": {
                         "interval_step_ms_mean": 4000.0,
                         "interval_step_ms_count": 5}}}])
    assert obs_main(["costmodel", "predict", model_path, blown]) == 1
    # no usable cells on either side -> 2
    empty = _write(tmp_path / "empty.json", [])
    assert obs_main(["costmodel", "fit", empty]) == 2
    assert obs_main(["costmodel", "predict", model_path, empty]) == 2


def test_workload_drift_cell_detects_all_phases(monkeypatch):
    """The bench cell end-to-end at a miniature rate: 3 transitions
    detected, stable arm clean, extras present on the result (the
    aligned-pipeline overhead arm is stubbed — its compile cost belongs
    to the recorded cell, not the unit suite)."""
    from scotty_tpu.bench import runner
    from scotty_tpu.bench.harness import BenchmarkConfig

    monkeypatch.setattr(runner, "measure_workload_overhead",
                        lambda **kw: 0.0)
    cfg = BenchmarkConfig(name="wd-mini", throughput=256,
                          watermark_period_ms=1000, max_lateness=4000,
                          n_keys=16, seed=3)
    res = runner.run_cell(cfg, "Tumbling(1000)", "sum", "WorkloadDrift")
    assert res.drift_all_detected is True
    assert res.drift_false_positives == 0
    assert set(res.drift_detect_lags) \
        == {"rate_x8", "late_storm", "key_skew"}
    assert all(0 < lag <= 4 for lag in res.drift_detect_lags.values())
    assert res.metrics["fingerprint"]["features"]
    assert res.n_tuples > 0 and res.n_windows_emitted > 0


def test_recorded_drift_cell_acceptance_artifact():
    """The checked-in workload-drift cell must carry the acceptance
    evidence: all 3 phase transitions detected within the bounded
    window, zero stable-arm false positives, sensor-plane A/B within
    the 2% overhead band."""
    path = os.path.join(RESULTS, "result_workload-drift.json")
    with open(path) as f:
        (cell,) = json.load(f)
    assert cell["drift_all_detected"] is True
    assert cell["drift_false_positives"] == 0
    lags = cell["drift_detect_lags"]
    assert set(lags) == {"rate_x8", "late_storm", "key_skew"}
    assert all(0 < lag <= 4 for lag in lags.values())
    assert cell["workload_overhead_pct_median"] <= 2.0
    assert cell["metrics"]["fingerprint"]["features"]
