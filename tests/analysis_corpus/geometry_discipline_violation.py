"""Seeded violation: coupled retunable knobs co-constructed raw (the
ISSUE 18 config-scatter shape) — the batch span is spelled twice and
the ring block a third time, free to drift apart, and the resulting
engine runs at a geometry no cache key or checkpoint sidecar names."""

from scotty_tpu.engine.config import EngineConfig
from scotty_tpu.ingest import RingConfig
from scotty_tpu.shaper import ShaperConfig


def build_engine(capacity, batch):
    econf = EngineConfig(capacity=capacity, batch_size=batch)
    sconf = ShaperConfig(late_capacity=max(64, batch // 8))
    return econf, sconf


def build_feed(batch, depth):
    ring = RingConfig(depth=depth, block_size=batch)
    econf = EngineConfig(batch_size=batch, micro_batch=4)
    return ring, econf
