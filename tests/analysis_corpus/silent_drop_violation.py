"""Seeded violation: broad except handlers that swallow data-path
errors without evidence — holes in the tuple-conservation audit."""


def deliver_all(records, sink):
    delivered = 0
    for rec in records:
        try:
            sink(rec)
            delivered += 1
        except Exception:              # fires silent-drop
            pass
    return delivered


def pump(source, op):
    while True:
        try:
            op.process_element(*next(source))
        except StopIteration:
            break
        except:                        # noqa: E722 — fires silent-drop
            continue
