"""Framework corpus: a reasonless allow comment — the underlying
finding still counts AND the comment itself is a suppression-format
finding ("zero findings left unexplained" is the acceptance bar)."""


def emit(row):
    print("row:", row)      # scotty: allow(no-print)
