"""Seeded violation: string-literal flight-event kinds (the ISSUE 6
review finding) — a typo'd literal records events the postmortem
classifier silently fails to match."""


def on_overflow(obs, exc, flight):
    obs.flight_event("overlow", "slice_store", 1.0)   # typo'd literal
    obs.record_failure(exc, kind="overflow")          # literal kind
    flight.record("shed", "admission", 3.0)
    obs.flight.record("watermark", "watermark", 100.0)
