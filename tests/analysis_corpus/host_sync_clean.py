"""Clean twin: dispatch stays async; the round trip lives in the
documented drain-point functions."""

import jax


class HotLoop:
    def run(self, n):
        out = []
        for i in range(n):
            out.append(self._step(self.state, i))
        return out                  # handles only — no sync

    def sync(self):
        return int(jax.device_get(self.state.n_slices))

    def check_overflow(self):
        if bool(jax.device_get(self.state.overflow)):
            raise RuntimeError("overflow")
