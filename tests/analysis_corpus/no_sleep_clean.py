"""Clean twin: waits ride the injectable Clock (chaos tests pass a
ManualClock); asyncio.sleep is event-loop time, not a wall-clock
stall."""

import asyncio


def backoff(clock, delay_s):
    clock.sleep(delay_s)


async def poll_tick(delay_s):
    await asyncio.sleep(delay_s)
