"""Seeded violation: bare print in engine-silence scope."""


def emit_result(row):
    print("result:", row)          # fires no-print
    return row
