"""Clean twin: timestamps from resilience.clock; perf_counter stays
allowed for relative durations."""

import time


def export_row(clock, value, wall_time):
    t0 = time.perf_counter()
    row = {"t": wall_time(), "event_t": clock.now(), "v": value}
    row["build_ms"] = (time.perf_counter() - t0) * 1e3
    return row
