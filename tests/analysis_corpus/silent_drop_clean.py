"""Clean twin: broad handlers leave evidence (count, dead-letter, or
re-raise); narrow typed handlers are ordinary control flow."""


def deliver_all(records, sink, obs, poison):
    delivered = 0
    for rec in records:
        try:
            sink(rec)
            delivered += 1
        except Exception as e:         # counted + dead-lettered
            obs.counter("resilience_poison_records").inc()
            poison.handle(rec, e)
    return delivered


def pump(source, op):
    while True:
        try:
            op.process_element(*next(source))
        except StopIteration:          # narrow: ordinary control flow
            break
        except Exception:
            raise                      # re-raise is evidence
