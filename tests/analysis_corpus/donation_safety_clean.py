"""Clean twin: the carry idiom (donated arg rebound in the same
statement) and restore via an XLA-owned copy."""

import jax
import numpy as np


class Pipeline:
    def build(self, step):
        self._step = jax.jit(step, donate_argnums=(0,))

    def good_carry(self):
        self.state, res = self._step(self.state, 1)
        return float(res)

    def good_restore(self, saved_leaves):
        host = np.asarray(saved_leaves[0])
        owned = jax.device_put(host)        # XLA-owned materialization
        self.state, res = self._step(owned, 1)
        return res
