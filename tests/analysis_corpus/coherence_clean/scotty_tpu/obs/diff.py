"""Mini-tree corpus (clean twin): every gated name resolves."""

DEFAULT_THRESHOLDS = {
    "metrics": {
        "engine_tuples": {"direction": "higher"},
        "resilience_shed_tuples": {"direction": "lower", "default": 0},
        "serving_tenant_active_t0": {"direction": "lower"},
    },
    "require_cells": True,
}
