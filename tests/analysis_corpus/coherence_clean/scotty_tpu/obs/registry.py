"""Mini-tree corpus (clean twin): the created names, including a
dynamic per-tenant prefix that anchors placeholder doc spellings."""

RESILIENCE_SHED_TUPLES = "resilience_shed_tuples"


def wire(registry, tenant):
    registry.counter(RESILIENCE_SHED_TUPLES)
    registry.counter("engine_tuples")
    registry.gauge(f"serving_tenant_active_{tenant}")
