"""Mini-tree corpus: the names the code ACTUALLY creates (note the
plural ``resilience_shed_tuples`` — the threshold file dropped the
's', the classic typo'd-gate drift)."""

RESILIENCE_SHED_TUPLES = "resilience_shed_tuples"


def wire(registry):
    registry.counter(RESILIENCE_SHED_TUPLES)
    registry.counter("engine_tuples")
