"""Mini-tree corpus: a threshold gate keyed on a metric nothing
creates — it silently gates nothing."""

DEFAULT_THRESHOLDS = {
    "metrics": {
        "engine_tuples_total": {"direction": "higher"},
        "resilience_shed_tuple": {"direction": "lower", "default": 0},
    },
    "require_cells": True,
}
