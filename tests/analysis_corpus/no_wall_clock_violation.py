"""Seeded violation: bare wall-clock reads in an export path."""

import time
from time import monotonic


def export_row(value):
    return {"t": time.time(), "mono": monotonic(), "v": value}
