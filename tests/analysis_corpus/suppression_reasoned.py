"""Framework corpus: a violation silenced by a REASONED suppression —
reported as suppressed, never as new."""


def emit(row):
    # scotty: allow(no-print) — corpus fixture proving the reasoned
    # form silences the finding
    print("row:", row)
