"""Seeded violation: host syncs outside the sanctioned drain points —
the zero-sync fused-step contract's creep class."""

import jax


class HotLoop:
    def run(self, n):
        out = []
        for i in range(n):
            res = self._step(self.state, i)
            jax.block_until_ready(res)            # fires host-sync
            out.append(jax.device_get(res))       # fires host-sync
            if self.state.overflow.item():        # fires host-sync
                break
        return out
