"""Clean twin: every committed byte rides fsio — intent digests
recorded, crash-point fuzzer can interpose, replace is durable."""

import io
import json
import pickle

import numpy as np

from scotty_tpu.utils import fsio


def commit_state(path, doc, leaves, op):
    fsio.write_bytes(path + ".tmp", json.dumps(doc).encode())
    buf = io.BytesIO()
    # scotty: allow(fsio-discipline) — serializes into an in-memory
    # BytesIO; the bytes commit via fsio.write_bytes below
    np.savez(buf, *leaves)
    fsio.write_bytes(path + ".npz", buf.getvalue())
    fsio.write_bytes(path + ".pkl", pickle.dumps(op))
    fsio.replace(path + ".tmp", path)


def read_back(path):
    with open(path) as f:           # reads are not commits
        return json.load(f)
