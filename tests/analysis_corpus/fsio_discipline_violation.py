"""Seeded violation: the ISSUE 8 bug class — committed state written
around utils.fsio (a silent short write would be blessed by a
disk-bytes manifest fallback and restore would crash-loop)."""

import json
import os
import pickle

import numpy as np


def commit_state(path, doc, leaves, op):
    with open(path + ".tmp", "w") as f:       # fires fsio-discipline
        json.dump(doc, f)                     # fires fsio-discipline
    np.savez(path + ".npz", *leaves)          # fires fsio-discipline
    with open(path + ".pkl", "wb") as g:      # fires fsio-discipline
        pickle.dump(op, g)                    # fires fsio-discipline
    os.replace(path + ".tmp", path)           # fires fsio-discipline
