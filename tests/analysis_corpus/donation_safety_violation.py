"""Seeded violation: both arms of the ISSUE 2 donation bug class —
use-after-donation and a numpy-backed leaf into a donating kernel
(the checkpoint-restore segfault)."""

import jax
import numpy as np


class Pipeline:
    def build(self, step):
        self._step = jax.jit(step, donate_argnums=(0,))

    def bad_use_after(self):
        res = self._step(self.state, 1)
        return float(self.state.sum()) + res      # read after donation

    def bad_restore(self, saved_leaves):
        host = np.asarray(saved_leaves[0])        # CPU zero-copy leaf
        res = self._step(host, 1)                 # host memory donated
        return res
