"""Clean twin: kinds come from the obs.flight constant vocabulary;
variables pass (the framework can't resolve them, and the constants
they carry were checked at their own call sites)."""

from scotty_tpu.obs import flight as _flight


def on_overflow(obs, exc, kind):
    obs.flight_event(_flight.OVERFLOW, "slice_store", 1.0)
    obs.record_failure(exc, kind=_flight.OVERFLOW)
    obs.flight.record(_flight.WATERMARK, "watermark", 100.0)
    obs.flight_event(kind, "forwarded", 0.0)      # variable: passes
