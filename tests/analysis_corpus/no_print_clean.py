"""Clean twin: output through an overridable echo sink."""


def emit_result(row, echo):
    echo(f"result: {row}")
    return row
