"""Clean twin: the coupled knobs live in one EngineGeometry and the
per-module configs are DERIVED — and a single config class with
retunable kwargs passes (nothing to couple), as do constructions whose
kwargs are all non-retunable (their source of truth stays per-module)."""

from scotty_tpu.autotune import EngineGeometry
from scotty_tpu.engine.config import EngineConfig
from scotty_tpu.shaper import ShaperConfig


def build_engine(capacity, batch):
    geom = EngineGeometry(capacity=capacity, batch_size=batch,
                          late_capacity=max(64, batch // 8))
    return geom.engine_config(), geom.shaper_config()


def build_single(capacity):
    # one class alone: no coupling to drift
    return EngineConfig(capacity=capacity, annex_capacity=8)


def build_non_retunable():
    # non-retunable kwargs never count, even across two classes
    econf = EngineConfig(overflow_policy="grow", annex_capacity=16)
    sconf = ShaperConfig(late_routing="combined")
    return econf, sconf
