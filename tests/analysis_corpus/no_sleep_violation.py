"""Seeded violation: wall-clock sleeps instead of the injectable
Clock."""

import time
from time import sleep


def backoff(delay_s):
    time.sleep(delay_s)            # fires no-sleep
    sleep(delay_s)                 # fires no-sleep (imported form)
