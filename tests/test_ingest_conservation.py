"""Cross-connector tuple-conservation property suite (ISSUE 7 satellite).

For every run loop × chaos class × seed: drive the loop through the
ingest ring with an Observability attached and assert the EXACT
conservation identity over the contract counters —

    seen == ingest_ring_delivered + ingest_ring_shed
            + held(=0 after drain) + resilience_poison_records

plus the internal consistency ``ingest_ring_offered == delivered + shed
- (records never offered because they were poison)`` and the operator-
side ``ingest_tuples == delivered``. One missing tuple anywhere fails
the identity — this is the suite that turns "no silent drops" from a
claim into a property.
"""

import asyncio

import numpy as np
import pytest

from scotty_tpu.connectors.base import (
    AscendingWatermarks,
    KeyedScottyWindowOperator,
)
from scotty_tpu.connectors.iterable import run_global, run_keyed
from scotty_tpu.core.aggregates import SumAggregation
from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
from scotty_tpu.ingest import RingConfig
from scotty_tpu.obs import Observability
from scotty_tpu.resilience import chaos
from scotty_tpu.resilience.connectors import retrying_source
from scotty_tpu.resilience.clock import ManualClock

Time = WindowMeasure.Time

SEEDS = [0, 1]
CHAOS = ["burst", "late_storm", "flaky", "poison"]


def _records(kind: str, seed: int, n: int = 240, keyed: bool = True):
    """A record stream of the given chaos class. Returns
    ``(records, n_poison)`` — poison records are malformed on purpose."""
    rng = chaos.rng_of(seed)
    if kind == "burst":
        # disorder bounded WITHIN allowed_lateness (4000) — the stream
        # contract every loop already enforces; unrepairable records are
        # the drop counters' business, not conservation's
        base = np.arange(n) * 30
        ts = np.maximum(base + rng.integers(-2000, 2000, n), 0)
        vals = rng.integers(0, 100, n).astype(np.float32)
    elif kind == "late_storm":
        head_v, head_t = chaos.burst(seed, n // 2, 0, 8_000)
        late_v, late_t = chaos.late_storm(seed + 1, n - n // 2,
                                          now_ts=8_000,
                                          max_lateness=4_000)
        vals = np.concatenate([head_v, late_v])
        ts = np.concatenate([head_t, late_t])
    else:
        vals, ts = chaos.burst(seed, n, 0, 8_000)
    keys = rng.integers(0, 3, vals.size)
    if keyed:
        recs = [(f"k{int(k)}", float(v), int(t))
                for k, v, t in zip(keys, vals, ts)]
    else:
        recs = [(float(v), int(t)) for v, t in zip(vals, ts)]
    n_poison = 0
    if kind == "poison":
        idx = sorted(rng.choice(n, size=max(1, n // 20),
                                replace=False).tolist())
        for i in idx:
            recs[i] = recs[i][:-1]       # wrong arity → dead-letter
        n_poison = len(idx)
    return recs, n_poison


def _mk_keyed(obs):
    return KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=4000,
        watermark_policy=AscendingWatermarks(), obs=obs)


def _assert_identity(obs, n_seen: int, n_poison: int,
                     expect_shed: int = 0):
    snap = obs.registry.snapshot()
    offered = int(snap.get("ingest_ring_offered", 0))
    delivered = int(snap.get("ingest_ring_delivered", 0))
    shed = int(snap.get("ingest_ring_shed", 0))
    held = int(snap.get("ingest_ring_occupancy", 0))
    dead = int(snap.get("resilience_poison_records", 0))
    # the ISSUE 7 identity, exact: every record the loop pulled is
    # delivered, shed, still held (0 after drain) or dead-lettered
    assert n_seen == delivered + shed + held + dead, snap
    assert held == 0                     # drained
    assert dead == n_poison
    # ring-internal consistency: accepted records are delivered or held
    # (shed records never entered the ring — they were refused at the
    # boundary and counted there)
    assert offered == delivered + held
    if expect_shed == 0:
        assert shed == 0
    else:
        assert shed == expect_shed
    # operator-side agreement: every delivered record was ingested
    assert int(snap.get("ingest_tuples", 0)) == delivered
    return snap


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("kind", CHAOS)
def test_iterable_keyed_conservation(kind, seed):
    recs, n_poison = _records(kind, seed)
    obs = Observability()
    op = _mk_keyed(obs)
    src = iter(recs)
    if kind == "flaky":
        flaky = chaos.FlakySource(recs, fail_at={40, 111})
        src = retrying_source(flaky, clock=ManualClock(), obs=obs)
    list(run_keyed(src, op,
                   ingest_ring=RingConfig(depth=4, block_size=16)))
    _assert_identity(obs, len(recs), n_poison)


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("kind", CHAOS)
def test_iterable_global_conservation(kind, seed):
    from scotty_tpu.connectors.base import GlobalScottyWindowOperator

    recs, n_poison = _records(kind, seed, keyed=False)
    obs = Observability()
    op = GlobalScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=4000,
        watermark_policy=AscendingWatermarks(), obs=obs)
    src = iter(recs)
    if kind == "flaky":
        flaky = chaos.FlakySource(recs, fail_at={25})
        src = retrying_source(flaky, clock=ManualClock(), obs=obs)
    # poison for the GLOBAL loop: a 1-tuple fails (v, ts) destructure
    list(run_global(src, op,
                    ingest_ring=RingConfig(depth=4, block_size=16)))
    _assert_identity(obs, len(recs), n_poison)


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("kind", ["burst", "late_storm", "flaky",
                                  "poison"])
def test_kafka_conservation(kind, seed):
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator

    records = chaos.make_records(seed=seed, n=200, keys=3, period_ms=40)
    n_poison = 0
    if kind == "poison":
        records, idx = chaos.corrupt_records(records, seed=seed + 5,
                                             pct=0.05)
        n_poison = len(idx)
    elif kind == "late_storm":
        # reorder timestamps: a late half behind the head
        half = len(records) // 2
        for r in records[half:]:
            r.timestamp = max(0, r.timestamp - 3000)
    obs = Observability()
    op = _mk_keyed(obs)
    src = records
    if kind == "flaky":
        flaky = chaos.FlakySource(records, fail_at={60})
        src = retrying_source(flaky, clock=ManualClock(), obs=obs)
    KafkaScottyWindowOperator(operator=op).run(
        src, lambda item: None,
        ingest_ring=RingConfig(depth=4, block_size=16))
    _assert_identity(obs, len(records), n_poison)


@pytest.mark.parametrize("seed", SEEDS)
@pytest.mark.parametrize("kind", ["burst", "late_storm"])
def test_asyncio_conservation(kind, seed):
    from scotty_tpu.connectors.asyncio_connector import run_keyed_async

    recs, n_poison = _records(kind, seed)
    obs = Observability()
    op = _mk_keyed(obs)

    async def source():
        for r in recs:
            yield r

    asyncio.run(run_keyed_async(
        source(), op, lambda item: None,
        ingest_ring=RingConfig(depth=4, block_size=16)))
    _assert_identity(obs, len(recs), n_poison)


@pytest.mark.parametrize("seed", SEEDS)
def test_shed_path_conservation(seed):
    """The shed arm of the identity: policy='shed' with manual pumping
    sheds everything past ring capacity, and the identity must hold
    with the exact shed count on the counters."""
    recs, _ = _records("burst", seed)
    obs = Observability()
    op = _mk_keyed(obs)
    shed_seen = []
    list(run_keyed(iter(recs), op,
                   ingest_ring=RingConfig(depth=2, block_size=8,
                                          policy="shed", pump_at=0),
                   shed_callback=lambda v, t, k: shed_seen.extend(t)))
    snap = _assert_identity(obs, len(recs), 0,
                            expect_shed=len(recs) - 16)
    assert len(shed_seen) == int(snap["ingest_ring_shed"])


def test_dead_letter_path_receives_the_poison_records():
    recs, n_poison = _records("poison", 3)
    obs = Observability()
    op = _mk_keyed(obs)
    letters = []
    list(run_keyed(iter(recs), op,
                   ingest_ring=RingConfig(depth=4, block_size=16),
                   dead_letter=lambda r, e: letters.append(r)))
    assert len(letters) == n_poison
    _assert_identity(obs, len(recs), n_poison)
