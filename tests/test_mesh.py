"""Mesh-sharded keyed engine (ISSUE 10): shard_map execution, routing,
hot-key rebalance at checkpoint boundaries, and shard-count-portable
checkpoints — all differential against per-key host simulators and
never-rebalanced engine twins (conftest provides the virtual 8-device
CPU mesh)."""

import json
import os

import numpy as np
import pytest

from scotty_tpu import (
    CountMinSketchAggregation,
    MaxAggregation,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.mesh import (
    MeshKeyedEngine,
    MeshKeyedPipeline,
    RoutingTable,
    plan_rebalance,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 10, batch_size=32, annex_capacity=128,
                   min_trigger_pad=32)
WINDOWS = [TumblingWindow(Time, 20), SlidingWindow(Time, 50, 10)]


# ---------------------------------------------------------------------------
# Routing table + planner (pure host)
# ---------------------------------------------------------------------------


def test_routing_table_identity_and_swaps():
    t = RoutingTable(16, 4)
    assert t.rows_per_shard == 4
    assert (t.shard_of([0, 5, 15]) == [0, 1, 3]).all()
    t2 = t.swapped([(0, 12)])
    assert t2.shard_of([0])[0] == 3 and t2.shard_of([12])[0] == 0
    # permutation_from: applying it to row-major data relocates rows
    perm = t2.permutation_from(t)
    data = np.arange(16) * 10          # physical rows under t == keys
    moved = data[perm]
    assert moved[t2.row_of[0]] == 0 and moved[t2.row_of[12]] == 120


def test_routing_table_rejects_bad_shapes():
    with pytest.raises(ValueError):
        RoutingTable(10, 4)            # not divisible
    with pytest.raises(ValueError):
        RoutingTable(8, 4, row_of=np.zeros(8, np.int32))  # not a perm


def test_routing_table_json_roundtrip():
    t = RoutingTable(8, 2).swapped([(1, 6)])
    t2 = RoutingTable.from_json(t.to_json())
    assert (t2.row_of == t.row_of).all()
    assert t2.n_shards == 2


def test_plan_rebalance_balances_and_converges():
    t = RoutingTable(16, 4)
    loads = np.ones(16)
    loads[0], loads[1] = 10, 9          # two hot keys on shard 0
    swaps, stats = plan_rebalance(t, loads, max_moves=8)
    assert swaps and stats["imbalance_after"] < stats["imbalance_before"]
    nt = t.swapped(swaps)
    assert sorted(nt.row_of.tolist()) == list(range(16))
    # a single dominant key cannot be split: the planner must CONVERGE,
    # not oscillate the key between shards forever
    loads2 = np.ones(16)
    loads2[3] = 100.0
    swaps2, _ = plan_rebalance(t, loads2, max_moves=64)
    assert len(swaps2) < 64


# ---------------------------------------------------------------------------
# Engine: shard_map execution differential
# ---------------------------------------------------------------------------


def _keyed_oracle(n_keys, windows, agg_factories, streams, wm,
                  lateness=1000):
    out = {}
    for k in range(n_keys):
        op = SlicingWindowOperator()
        for w in windows:
            op.add_window_assigner(w)
        for mk in agg_factories:
            op.add_aggregation(mk())
        op.set_max_lateness(lateness)
        for v, t in streams(k):
            op.process_element(float(v), int(t))
        out[k] = [w for w in op.process_watermark(wm) if w.has_value()]
    return out


def _hot_stream(seed=11, n_keys=16, n=800, hot=3, t_hi=300):
    rng = np.random.default_rng(seed)
    keys = rng.integers(0, n_keys, size=n)
    keys[: n // 3] = hot
    ts = np.sort(rng.integers(0, t_hi, size=n))
    vals = rng.integers(1, 50, size=n).astype(np.float64)
    return keys, vals, ts


def _make_engine(n_keys=16, n_shards=8):
    eng = MeshKeyedEngine(n_keys=n_keys, n_shards=n_shards, config=CFG)
    for w in WINDOWS:
        eng.add_window_assigner(w)
    eng.add_aggregation(SumAggregation())
    eng.add_aggregation(MaxAggregation())
    return eng


def test_mesh_engine_matches_per_key_simulators():
    keys, vals, ts = _hot_stream()
    eng = _make_engine()
    eng.process_keyed_elements(keys, vals, ts)
    wm = int(ts[-1]) + 1
    got = eng.process_watermark(wm)
    want = _keyed_oracle(16, WINDOWS, [SumAggregation, MaxAggregation],
                         lambda k: zip(vals[keys == k], ts[keys == k]), wm)
    got_by_key = {k: [] for k in range(16)}
    for k, w in got:
        got_by_key[k].append(w)
    for k in range(16):
        assert len(got_by_key[k]) == len(want[k]), k
        for a, b in zip(want[k], got_by_key[k]):
            assert (a.get_start(), a.get_end()) == (b.get_start(),
                                                    b.get_end())
            for x, y in zip(a.get_agg_values(), b.get_agg_values()):
                assert float(x) == pytest.approx(float(y), rel=1e-5)


def test_mesh_engine_global_fold_is_in_executable_psum():
    """query_global folds all-shard totals via psum/pmin/pmax INSIDE one
    lowered program (the global_op.py seam on the keyed path)."""
    keys, vals, ts = _hot_stream()
    eng = _make_engine()
    eng.process_keyed_elements(keys, vals, ts)
    cnt, lowered = eng.query_global([0], [300])
    assert int(cnt[0]) == len(keys)
    assert float(lowered[0][0]) == pytest.approx(float(vals.sum()),
                                                 rel=1e-6)
    assert float(lowered[1][0]) == float(vals.max())
    # the collective is in the executable, not a host-side reduction
    import jax

    low = jax.jit(eng._global_query_fn).lower(
        eng._state, np.zeros(32, np.int64), np.full(32, 300, np.int64),
        np.arange(32) < 1)
    assert low.as_text().count("all-reduce") \
        + low.as_text().count("all_reduce") >= 2


def test_mesh_engine_rebalance_bitmatches_unmoved_oracle():
    keys, vals, ts = _hot_stream()
    rng = np.random.default_rng(5)
    more_keys = rng.integers(0, 16, size=200)
    more_ts = np.sort(rng.integers(300, 500, size=200))
    more_vals = rng.integers(1, 50, size=200).astype(np.float64)

    def feed(eng, rebalance):
        eng.process_keyed_elements(keys, vals, ts)
        first = eng.process_watermark(int(ts[-1]) + 1)
        if rebalance:
            swaps, stats = eng.detect_hot_keys(max_moves=8)
            assert 3 in stats["hot_keys"]          # the planted hot key
            eng.rebalance(swaps)
        eng.process_keyed_elements(more_keys, more_vals, more_ts)
        return first, eng.process_watermark(501)

    f1, got = feed(_make_engine(), rebalance=True)
    f2, want = feed(_make_engine(), rebalance=False)
    assert len(got) == len(want) and len(f1) == len(f2)
    for (ka, wa), (kb, wb) in zip(want, got):
        assert ka == kb
        assert (wa.get_start(), wa.get_end()) == (wb.get_start(),
                                                  wb.get_end())
        for x, y in zip(wa.get_agg_values(), wb.get_agg_values()):
            assert float(x) == float(y), (ka, wa.get_start())


def test_mesh_engine_device_round_routes_through_table():
    """ingest_device_round(logical_major=True): a device-resident
    logical-major [K, B] round lands on the right physical rows via the
    DEVICE routing table — including after a rebalance made the table
    non-identity — and results match per-key host simulators."""
    import jax
    import jax.numpy as jnp

    K, B = 16, 32
    rng = np.random.default_rng(2)
    eng = _make_engine(n_keys=K)
    # seed some state, checkpoint-boundary-style flush, then rebalance so
    # the routing table is NOT the identity
    eng.process_keyed_elements([0], [1.0], [0])
    _ = eng.process_watermark(1)
    eng.rebalance([(0, 9), (3, 12)])
    assert eng.routing.row_of[0] == 9

    all_rows = {k: [(1.0, 0)] if k == 0 else [] for k in range(K)}
    lo = 1
    for _ in range(3):
        ts = np.sort(rng.integers(lo, lo + 50, size=(K, B)),
                     axis=1).astype(np.int64)
        vals = rng.integers(1, 9, size=(K, B)).astype(np.float32)
        eng.ingest_device_round(
            jax.device_put(jnp.asarray(ts)),
            jax.device_put(jnp.asarray(vals)),
            jax.device_put(np.ones((K, B), bool)), lo, lo + 49)
        for k in range(K):
            all_rows[k].extend(zip(vals[k], ts[k]))
        lo += 50
    wm = lo + 100
    got = eng.process_watermark(wm)
    want = _keyed_oracle(K, WINDOWS, [SumAggregation, MaxAggregation],
                         lambda k: all_rows[k], wm)
    got_by_key = {k: [] for k in range(K)}
    for k, w in got:
        got_by_key[k].append(w)
    for k in range(K):
        assert len(got_by_key[k]) == len(want[k]), k
        for a, b in zip(want[k], got_by_key[k]):
            assert (a.get_start(), a.get_end()) == (b.get_start(),
                                                    b.get_end())
            for x, y in zip(a.get_agg_values(), b.get_agg_values()):
                assert float(x) == pytest.approx(float(y), rel=1e-5), k


def test_mesh_engine_rejects_rebalance_with_pending_rounds():
    eng = _make_engine()
    eng.process_keyed_elements([1], [1.0], [10])
    with pytest.raises(RuntimeError, match="checkpoint"):
        eng.rebalance([(0, 8)])


def test_mesh_checkpoint_restores_under_different_shard_counts(tmp_path):
    """Save under 8 shards, restore under 2 and 1 (and after the saver
    rebalanced): every restore continues the stream bit-identically."""
    keys, vals, ts = _hot_stream()
    rng = np.random.default_rng(7)
    more_keys = rng.integers(0, 16, size=200)
    more_ts = np.sort(rng.integers(300, 500, size=200))
    more_vals = rng.integers(1, 50, size=200).astype(np.float64)

    eng = _make_engine(n_shards=8)
    eng.process_keyed_elements(keys, vals, ts)
    _ = eng.process_watermark(int(ts[-1]) + 1)
    ck = str(tmp_path / "ck")
    eng.save(ck)

    def finish(e):
        e.process_keyed_elements(more_keys, more_vals, more_ts)
        return e.process_watermark(501)

    want = finish(eng)
    for m in (2, 1):
        e2 = _make_engine(n_shards=m)
        e2.restore(ck)
        got = finish(e2)
        assert len(got) == len(want), m
        for (ka, wa), (kb, wb) in zip(want, got):
            assert ka == kb and wa.get_start() == wb.get_start()
            for x, y in zip(wa.get_agg_values(), wb.get_agg_values()):
                assert float(x) == float(y), (m, ka)


def test_mesh_checkpoint_rejects_wrong_key_count(tmp_path):
    eng = _make_engine(n_keys=16)
    eng.process_keyed_elements([1], [1.0], [10])
    _ = eng.process_watermark(11)
    ck = str(tmp_path / "ck")
    eng.save(ck)
    other = MeshKeyedEngine(n_keys=32, n_shards=8, config=CFG)
    for w in WINDOWS:
        other.add_window_assigner(w)
    other.add_aggregation(SumAggregation())
    other.add_aggregation(MaxAggregation())
    with pytest.raises(ValueError, match="16 keys"):
        other.restore(ck)


# ---------------------------------------------------------------------------
# Supervisor boundary: atomic commit, rebalance after the commit point,
# corrupt newest bundle -> lineage fallback (the PR 8 machinery)
# ---------------------------------------------------------------------------


def test_supervisor_checkpoint_and_rebalance_with_lineage_fallback(
        tmp_path):
    import scotty_tpu.obs as obs_mod
    from scotty_tpu.resilience.supervisor import Supervisor

    keys, vals, ts = _hot_stream()
    rng = np.random.default_rng(9)
    mid_keys = rng.integers(0, 16, size=200)
    mid_ts = np.sort(rng.integers(300, 500, size=200))
    mid_vals = rng.integers(1, 50, size=200).astype(np.float64)
    late_keys = rng.integers(0, 16, size=150)
    late_ts = np.sort(rng.integers(500, 700, size=150))
    late_vals = rng.integers(1, 50, size=150).astype(np.float64)

    obs = obs_mod.Observability()
    sup = Supervisor(str(tmp_path / "sup"), obs=obs, keep_checkpoints=3)
    eng = _make_engine()
    eng.set_observability(obs)
    eng.process_keyed_elements(keys, vals, ts)
    _ = eng.process_watermark(int(ts[-1]) + 1)
    stats = eng.checkpoint_and_rebalance(sup, pos=1, max_moves=8)
    assert stats["moved"] > 0                       # planted hot key moved
    snap = obs.registry.snapshot()
    assert snap.get("mesh_rebalances") == 1
    assert snap.get("mesh_hot_keys", 0) >= 1

    eng.process_keyed_elements(mid_keys, mid_vals, mid_ts)
    _ = eng.process_watermark(501)
    eng.checkpoint_and_rebalance(sup, pos=2, max_moves=8)

    # corrupt the NEWEST generation's state payload: restores must fall
    # back through the lineage to ckpt-1 (counted, not fatal)
    ck2 = os.path.join(str(tmp_path / "sup"), "ckpt-2")
    target = os.path.join(ck2, "mesh_state.npz")
    blob = bytearray(open(target, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(target, "wb").write(bytes(blob))

    obs2 = obs_mod.Observability()
    sup2 = Supervisor(str(tmp_path / "sup"), obs=obs2, keep_checkpoints=3)
    found = sup2.latest_checkpoint()
    assert found is not None
    ck, _off = found
    assert os.path.basename(ck) == "ckpt-1"
    snap2 = obs2.registry.snapshot()
    assert snap2.get("ckpt_integrity_failures") == 1
    assert snap2.get("ckpt_lineage_fallbacks") == 1

    # restore from the surviving generation and replay from its offset:
    # emissions bit-match an uninterrupted engine
    e2 = _make_engine(n_shards=4)
    e2.restore(ck, verify=False)        # lineage walk just verified it
    e2.process_keyed_elements(mid_keys, mid_vals, mid_ts)
    _ = e2.process_watermark(501)
    e2.process_keyed_elements(late_keys, late_vals, late_ts)
    got = e2.process_watermark(701)

    e3 = _make_engine()
    e3.process_keyed_elements(keys, vals, ts)
    _ = e3.process_watermark(int(ts[-1]) + 1)
    e3.process_keyed_elements(mid_keys, mid_vals, mid_ts)
    _ = e3.process_watermark(501)
    e3.process_keyed_elements(late_keys, late_vals, late_ts)
    want = e3.process_watermark(701)
    assert len(got) == len(want)
    for (ka, wa), (kb, wb) in zip(want, got):
        assert ka == kb and wa.get_start() == wb.get_start()
        for x, y in zip(wa.get_agg_values(), wb.get_agg_values()):
            assert float(x) == float(y)


# ---------------------------------------------------------------------------
# Fused pipeline: shard-count invariance, in-executable global fold,
# rebalance mid-run, portable snapshots
# ---------------------------------------------------------------------------


def _make_pipeline(n_shards, seed=13, n_keys=16):
    windows = [TumblingWindow(Time, 100), SlidingWindow(Time, 500, 100)]
    p = MeshKeyedPipeline(
        windows, [SumAggregation(), MaxAggregation()], n_keys=n_keys,
        n_shards=n_shards, config=CFG, throughput=n_keys * 2000,
        wm_period_ms=100, max_lateness=100, seed=seed, gc_every=3)
    p.reset()
    return p


def test_mesh_pipeline_shard_invariant_and_matches_simulator():
    p8, p1 = _make_pipeline(8), _make_pipeline(1)
    sim = SlicingWindowOperator()
    for w in p8.windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.add_aggregation(MaxAggregation())
    sim.set_max_lateness(100)
    kk_sim = 5
    for i in range(6):
        a, b = p8.run(1)[0], p1.run(1)[0]
        for kk in (0, 5, 15):
            ra = p8.lowered_results_for_key(a, kk)
            rb = p1.lowered_results_for_key(b, kk)
            assert ra == rb, (i, kk)
        vals, ts = p8.materialize_interval(i, kk_sim)
        order = np.argsort(ts, kind="stable")
        sim.process_elements(vals[order], ts[order])
        want = {}
        for w in sim.process_watermark((i + 1) * 100):
            if w.has_value():
                want.setdefault((w.get_start(), w.get_end()),
                                w.get_agg_values())
        got = {(s, e): v
               for (s, e, c, v) in p8.lowered_results_for_key(a, kk_sim)}
        assert set(got) == set(want), (i, set(want) ^ set(got))
        for k2 in want:
            for x, y in zip(want[k2], got[k2]):
                assert float(x) == pytest.approx(float(y), rel=2e-4)
    p8.check_overflow()
    p1.check_overflow()


def test_mesh_pipeline_global_fold_matches_shard_reduction():
    import jax

    p = _make_pipeline(8)
    out = p.run(3)[-1]
    ws, we, cnt, results, gcnt, gparts = jax.device_get(out)
    assert (gcnt == cnt.sum(axis=0)).all()
    assert np.allclose(np.asarray(gparts[0]),
                       np.asarray(results[0]).sum(axis=0))
    assert np.allclose(np.asarray(gparts[1]),
                       np.asarray(results[1]).max(axis=0))
    rows = p.lowered_global(out)
    assert rows and all(c > 0 for _, _, c, _ in rows)


def test_mesh_pipeline_midrun_rebalance_bitmatches():
    pr, pn = _make_pipeline(8, seed=21), _make_pipeline(8, seed=21)
    for _ in range(3):
        pr.run(1), pn.run(1)
    pr.sync()
    pr.rebalance([(0, 9), (5, 12)])
    assert pr.routing.row_of[0] == 9
    for i in range(3):
        a, b = pr.run(1)[0], pn.run(1)[0]
        for kk in (0, 3, 5, 9, 12):
            assert pr.lowered_results_for_key(a, kk) \
                == pn.lowered_results_for_key(b, kk), (i, kk)
    pr.check_overflow()


def test_mesh_pipeline_snapshot_portable_across_shard_counts(tmp_path):
    pr = _make_pipeline(8, seed=31)
    pr.run(3)
    pr.sync()
    pr.rebalance([(2, 11)])
    ck = str(tmp_path / "pck")
    pr.save(ck)
    p2 = _make_pipeline(2, seed=31)
    p2.restore(ck)
    assert p2._interval == pr._interval
    a, b = pr.run(1)[0], p2.run(1)[0]
    for kk in (0, 2, 7, 11):
        assert pr.lowered_results_for_key(a, kk) \
            == p2.lowered_results_for_key(b, kk)
    # routing travels as a readable sidecar
    doc = json.load(open(os.path.join(ck, "routing.json")))
    assert doc["n_shards"] == 8 and doc["n_keys"] == 16


def test_mesh_pipeline_sparse_cms_matches_host_oracle():
    """The count-min sketch rides the mesh keyed path (ISSUE 10 satellite:
    the sparse-lift seam through the sharded pipeline) — estimates
    bit-match the scalar-face oracle on the materialized stream."""
    agg = CountMinSketchAggregation(2500.0, depth=2, width=128)
    p = MeshKeyedPipeline(
        [TumblingWindow(Time, 100)], [agg], n_keys=8, n_shards=8,
        config=CFG, throughput=8 * 2000, wm_period_ms=100,
        max_lateness=100, seed=3, gc_every=4)
    p.reset()
    for i in range(3):
        out = p.run(1)[0]
        for kk in (0, 7):
            vals, _ts = p.materialize_interval(i, kk)
            rows = p.lowered_results_for_key(out, kk)
            assert rows
            for (s, e, c, v) in rows:
                part = [0] * (agg.depth * agg.width)
                for val in vals:        # one tumbling window per interval
                    part = agg.lift_and_combine(part, float(val))
                assert float(v[0]) == agg.lower(part), (i, kk, s, e)
    p.check_overflow()


def test_mesh_cell_in_fresh_interpreter_subprocess():
    """The virtual-8-device CI certification (ISSUE 10): a FRESH
    interpreter with ``XLA_FLAGS=--xla_force_host_platform_device_count
    =8`` set before any JAX import (the PR 2 isolation discipline — no
    inherited backend, no conftest ordering dependence) runs a sharded
    cell end to end: shard_map step, psum fold, rebalance, oracle
    match."""
    import subprocess
    import sys

    body = r"""
import os
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
import numpy as np
import jax
assert len(jax.devices()) == 8, jax.devices()
from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.engine import EngineConfig
from scotty_tpu.mesh import MeshKeyedPipeline
cfg = EngineConfig(capacity=64, annex_capacity=8, min_trigger_pad=32)
def make(n):
    p = MeshKeyedPipeline([TumblingWindow(WindowMeasure.Time, 1000)],
                          [SumAggregation()], n_keys=128, n_shards=n,
                          config=cfg, throughput=128 * 1000,
                          wm_period_ms=1000, max_lateness=1000, seed=2)
    p.reset()
    return p
p8, p1 = make(8), make(1)
for i in range(3):
    a, b = p8.run(1)[0], p1.run(1)[0]
    for kk in (0, 64, 127):
        assert p8.lowered_results_for_key(a, kk) \
            == p1.lowered_results_for_key(b, kk), (i, kk)
ws, we, cnt, results, gcnt, gparts = jax.device_get(p8.run(1)[0])
assert (gcnt == cnt.sum(axis=0)).all()
p1.run(1)            # keep the twin on the same interval
p8.sync(); p8.rebalance([(0, 64)])
a, b = p8.run(1)[0], p1.run(1)[0]
for kk in (0, 64):
    assert p8.lowered_results_for_key(a, kk) \
        == p1.lowered_results_for_key(b, kk)
p8.check_overflow(); p1.check_overflow()
print("MESH_SUBPROCESS_OK")
"""
    # scrubbed env: the child must build its OWN 8-device CPU backend
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    env["PYTHONPATH"] = os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))
    r = subprocess.run([sys.executable, "-c", body], env=env,
                       capture_output=True, text=True, timeout=420)
    assert r.returncode == 0 and "MESH_SUBPROCESS_OK" in r.stdout, (
        f"isolated mesh cell failed (rc={r.returncode}):\n"
        f"{r.stdout[-2000:]}\n{r.stderr[-2000:]}")


def test_mesh_bench_cell_smoke():
    """run_mesh_keyed_cell completes with the mesh contract fields
    (scaling arms + differential arms) on a small geometry."""
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_mesh_keyed_cell

    cfg = BenchmarkConfig(name="mesh-smoke", throughput=1 << 18,
                          runtime_s=2, capacity=64, n_keys=128,
                          watermark_period_ms=1000, max_lateness=1000)
    r = run_mesh_keyed_cell(cfg, "Tumbling(1000)", "sum")
    assert r.tuples_per_sec > 0
    assert r.n_shards == 8 and r.n_keys == 128
    assert r.oracle_match and r.rebalance_match
    assert r.tuples_per_sec_1shard > 0 and r.scaling_ratio > 0
    assert len(r.per_shard_occupancy) == 8
