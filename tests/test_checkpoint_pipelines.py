"""Kill-and-resume for the benchmark execution modes (VERDICT r4 item 9):
the keyed operator and the fused pipelines — the modes every benchmark
actually runs — snapshot mid-sweep and reproduce IDENTICAL window
results after restore (the stream is a pure function of (seed,
interval), so a restored pipeline continues the exact tuple sequence).

SUBPROCESS ISOLATION (ISSUE 2 satellite). Root cause of the pre-existing
tier-1 abort: each resume case traces a fused pipeline THREE times (the
killed run, the restored run, and the uninterrupted reference), and by
this point in a full sweep the process has already traced dozens of other
pipeline variants. JAX tracing + XLA lowering of the deeply-nested fused
steps (scan-of-ingest with per-aggregation fold chains) recurses on the C
stack; the cumulative depth eventually exhausts it and the interpreter
dies with a hard SIGABRT mid-trace ("Fatal Python error: Aborted" inside
run_resume_case) — an abort no pytest hook can catch, so the WHOLE sweep
used to stop here with every later test unreported. The same tests pass
in a fresh interpreter. Until the upstream tracing recursion is bounded,
the resume cases run in ONE pytest subprocess (fresh C stack, this module
only) driven by ``test_checkpoint_suite_in_subprocess``; set
``SCOTTY_CHECKPOINT_ISOLATED=1`` (the driver does) to run them directly.
"""

import os
import subprocess
import sys

import numpy as np
import pytest

import jax

ISOLATED = os.environ.get("SCOTTY_CHECKPOINT_ISOLATED") == "1"
#: the resume cases only run inside the isolation subprocess (or when a
#: user invokes the module directly with the env var set)
_inner = pytest.mark.skipif(
    not ISOLATED,
    reason="runs inside the fresh-interpreter subprocess driver "
           "(C-stack exhaustion in cumulative JAX tracing — see module "
           "docstring)")


def test_checkpoint_suite_in_subprocess():
    """Drive every resume case in ONE fresh interpreter: a crash there
    (the known C-stack abort) fails THIS test with the subprocess tail
    instead of killing the whole tier-1 sweep."""
    if ISOLATED:
        pytest.skip("already inside the isolation subprocess")
    # the child inherits the caller's JAX backend (tier-1 sets
    # JAX_PLATFORMS=cpu itself; on accelerator machines the resume
    # cases keep running against the real device)
    env = dict(os.environ, SCOTTY_CHECKPOINT_ISOLATED="1")
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "-q", "-p", "no:cacheprovider",
         "-p", "no:randomly", os.path.abspath(__file__)],
        env=env, capture_output=True, text=True, timeout=540)
    assert r.returncode == 0, (
        f"isolated checkpoint suite failed (rc={r.returncode}):\n"
        f"{r.stdout[-3000:]}\n{r.stderr[-1500:]}")

from scotty_tpu import (
    HyperLogLogAggregation,
    SessionWindow,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.utils.checkpoint import (
    restore_keyed_operator,
    restore_pipeline,
    save_keyed_operator,
    save_pipeline,
)

Time, Count = WindowMeasure.Time, WindowMeasure.Count
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


def fetch(outs):
    return jax.device_get(outs)


def rows_of(fetched):
    out = []
    for (ws, we, cnt, res) in fetched:
        ws, we, cnt = np.asarray(ws), np.asarray(we), np.asarray(cnt)
        for j in range(len(ws)):
            if cnt.ndim == 1 and cnt[j] > 0:
                out.append((int(ws[j]), int(we[j]), int(cnt[j]),
                            tuple(np.asarray(r[j]).ravel().round(3).tolist()
                                  for r in res)))
    return out


def keyed_rows(fetched):
    out = []
    for (ws, we, cnt, res) in fetched:
        cnt = np.asarray(cnt)
        out.append((np.asarray(ws).tolist(), cnt.round(0).tolist(),
                    [np.asarray(r).round(3).tolist() for r in res]))
    return out


def run_resume_case(make, n_before=3, n_after=3, rows=rows_of,
                    tmp_path=None):
    # killed-and-resumed run
    p1 = make()
    _ = fetch(p1.run(n_before))
    save_pipeline(p1, str(tmp_path / "ckpt"))
    del p1
    p2 = make()
    restore_pipeline(p2, str(tmp_path / "ckpt"))
    got_tail = rows(fetch(p2.run(n_after)))
    # the uninterrupted run's tail must match the resumed tail exactly
    full = fetch(make().run(n_before + n_after))
    assert rows(full[n_before:]) == got_tail, "resumed tail diverged"


@_inner
def test_aligned_pipeline_resume(tmp_path):
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def make():
        return AlignedStreamPipeline(
            [TumblingWindow(Time, 50), SlidingWindow(Time, 200, 50)],
            [SumAggregation()], config=CFG, throughput=20_000,
            wm_period_ms=100, max_lateness=100, seed=5, gc_every=10 ** 9)
    run_resume_case(make, tmp_path=tmp_path)


@_inner
def test_count_pipeline_resume(tmp_path):
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    def make():
        return CountStreamPipeline(
            [TumblingWindow(Count, 7), TumblingWindow(Time, 50)],
            [SumAggregation()], throughput=2000, wm_period_ms=100,
            max_lateness=100, seed=3, out_of_order_pct=0.3)
    run_resume_case(make, tmp_path=tmp_path)


@_inner
def test_session_pipeline_resume(tmp_path):
    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    def make():
        return SessionStreamPipeline(
            [SessionWindow(Time, 300), SlidingWindow(Time, 500, 100)],
            [HyperLogLogAggregation(6)], config=CFG, throughput=20_000,
            wm_period_ms=100, max_lateness=100, seed=2,
            session_config={"count": 3, "minGapMs": 300, "maxGapMs": 700})
    run_resume_case(make, n_before=4, n_after=6, tmp_path=tmp_path)


@_inner
def test_keyed_pipeline_resume(tmp_path):
    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    def make():
        return KeyedAlignedPipeline(
            [TumblingWindow(Time, 100)], [SumAggregation()], n_keys=8,
            config=EngineConfig(capacity=256, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=80_000, wm_period_ms=100, max_lateness=100, seed=7)
    run_resume_case(make, rows=keyed_rows, tmp_path=tmp_path)


@_inner
def test_keyed_operator_resume(tmp_path):
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    def make():
        op = KeyedTpuWindowOperator(4, config=EngineConfig(
            capacity=1 << 10, batch_size=64, min_trigger_pad=32))
        op.add_window_assigner(TumblingWindow(Time, 100))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(100)
        return op

    rng = np.random.default_rng(0)
    N = 400
    ts = np.sort(rng.integers(0, 800, size=N)).astype(np.int64)
    keys = rng.integers(0, 4, size=N).astype(np.int64)
    vals = rng.random(N).astype(np.float32)

    def feed(op, lo, hi, wm):
        for k, v, t in zip(keys[lo:hi], vals[lo:hi], ts[lo:hi]):
            op.process_element(int(k), float(v), int(t))
        out = op.process_watermark_arrays(wm)
        return [tuple(np.asarray(x).round(3).ravel().tolist())
                for x in out]

    ref_op = make()
    a = feed(ref_op, 0, 200, 400)
    b = feed(ref_op, 200, 400, 900)

    op1 = make()
    a1 = feed(op1, 0, 200, 400)
    save_keyed_operator(op1, str(tmp_path / "kop"))
    op2 = make()
    restore_keyed_operator(op2, str(tmp_path / "kop"))
    b2 = feed(op2, 200, 400, 900)
    assert a1 == a
    assert b2 == b


@_inner
def test_pipeline_restore_guards(tmp_path):
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def make(seed=5):
        return AlignedStreamPipeline(
            [TumblingWindow(Time, 50)], [SumAggregation()], config=CFG,
            throughput=20_000, wm_period_ms=100, max_lateness=100,
            seed=seed, gc_every=10 ** 9)

    p = make()
    with pytest.raises(ValueError, match="not started"):
        save_pipeline(p, str(tmp_path / "x"))
    p.run(1, collect=False)
    p.sync()
    save_pipeline(p, str(tmp_path / "x"))
    with pytest.raises(ValueError, match="seed mismatch"):
        restore_pipeline(make(seed=6), str(tmp_path / "x"))
