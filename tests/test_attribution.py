"""Per-tenant attribution differentials (ISSUE 19): the exact
conservation identity — per-tenant ledger sums equal the engine-level
serving counters — asserted under single-device churn with quota
shedding, a mesh reshard, and a supervisor crash/restore; plus the
top-k gauge folding preserving every family's total and the emission
(windows/repairs) accounting against independently tallied rows."""

import os

import numpy as np
import pytest

from scotty_tpu import obs as _obs
from scotty_tpu.core.aggregates import SumAggregation
from scotty_tpu.core.windows import (
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.obs import Observability
from scotty_tpu.obs.attribution import (
    ATTRIBUTION_FAMILIES,
    TenantAttribution,
    attribution_metric,
)
from scotty_tpu.resilience import ManualClock, Supervisor
from scotty_tpu.serving import QueryAdmission, QueryService

Time = WindowMeasure.Time
SMALL = EngineConfig(capacity=1 << 12, annex_capacity=8,
                     min_trigger_pad=32)
MESH_CFG = EngineConfig(capacity=64, annex_capacity=8, min_trigger_pad=32)


def make_service(windows=(), max_queries=64, quota=0, on_reject="fail",
                 obs=None, seed=7, min_slots=8):
    return QueryService(
        [SumAggregation()], slice_grid=100, max_window_size=4000,
        throughput=10_000, wm_period_ms=1000, max_lateness=1000,
        seed=seed, config=SMALL,
        admission=QueryAdmission(max_queries=max_queries,
                                 per_tenant_quota=quota,
                                 on_reject=on_reject),
        windows=list(windows), min_slots=min_slots, obs=obs)


# ---------------------------------------------------------------------------
# the ledger itself
# ---------------------------------------------------------------------------


def test_count_conservation_by_construction_and_unknown_family():
    att = TenantAttribution(clock=ManualClock())
    att.count("a", "windows", 3)
    att.count("b", "windows", 2)
    att.count("a", "rejected")
    assert att.totals()["windows"] == 5
    assert att.rollup()["a"]["windows"] == 3
    assert att.conservation_ok()
    att.count("a", "windows", 0)            # zero delta: no tenant churn
    assert att.totals()["windows"] == 5
    with pytest.raises(ValueError):
        att.count("a", "nonsense_family")
    # apportion_count folds exact largest-remainder shares
    shares = att.apportion_count("shed", 7, {"a": 3.0, "b": 1.0})
    assert sum(shares.values()) == 7
    assert att.totals()["shed"] == 7
    assert att.conservation_ok()


def test_topk_gauge_folding_preserves_family_sum():
    obs = Observability()
    att = obs.attach_attribution(
        clock=ManualClock(), top_k=2, gauge_families=("windows",),
        gauge_every=1)
    counts = {"alice": 10, "bob": 7, "carol": 3, "dave": 1}
    for t, n in counts.items():
        att.count(t, "windows", n)
    # one accounted tick emits the gauges (empty rows: ledger unchanged)
    att.account_rows({}, {}, watermark=0.0, wm_period_ms=1000.0)
    snap = obs.snapshot()
    named = {t: snap.get(attribution_metric("windows", t))
             for t in ("alice", "bob")}
    assert named == {"alice": 10, "bob": 7}
    assert snap["slo_tenant_windows_other"] == 3 + 1
    assert sum(named.values()) + snap["slo_tenant_windows_other"] \
        == att.totals()["windows"]
    # the folded tenants never got a named gauge
    assert attribution_metric("windows", "carol") not in snap


# ---------------------------------------------------------------------------
# single-device churn: ledger == engine counters, exactly
# ---------------------------------------------------------------------------


def test_churn_conservation_vs_engine_counters():
    obs = Observability()
    att = obs.attach_attribution(clock=ManualClock())
    svc = make_service(windows=[SlidingWindow(Time, 4000, 1000)],
                       max_queries=8, quota=2, on_reject="shed", obs=obs)
    tally = {t: {f: 0 for f in ATTRIBUTION_FAMILIES}
             for t in ("default", "alice", "bob")}
    tally["default"]["registered"] = 1         # the ctor's seed window

    handles = []
    pool = [TumblingWindow(Time, 500), TumblingWindow(Time, 1000),
            SlidingWindow(Time, 2000, 500)]
    rng = np.random.default_rng(3)
    for i in range(24):
        tenant = ("alice", "bob")[i % 2]
        w = pool[int(rng.integers(len(pool)))]
        h = svc.register(w, tenant=tenant)
        if h is None:
            tally[tenant]["rejected"] += 1
        else:
            tally[tenant]["registered"] += 1
            handles.append(h)
        if len(handles) > 2 and rng.random() < 0.5:
            victim = handles.pop(int(rng.integers(len(handles))))
            svc.cancel(victim)
            tally[victim.tenant]["cancelled"] += 1

    stats = svc.stats()
    totals = att.totals()
    for fam, counter in (("registered", "serving_registered"),
                         ("cancelled", "serving_cancelled"),
                         ("rejected", "serving_rejected")):
        assert totals[fam] == stats[counter], fam
        assert totals[fam] == sum(t[fam] for t in tally.values()), fam
    roll = att.rollup()
    for tenant, fams in tally.items():
        for fam, n in fams.items():
            if n:
                assert roll[tenant][fam] == n, (tenant, fam)
    assert att.conservation_ok()


def test_emission_accounting_matches_tallied_rows():
    obs = Observability()
    att = obs.attach_attribution(clock=ManualClock())
    svc = make_service(windows=[TumblingWindow(Time, 1000)], obs=obs,
                       max_queries=8)
    h_a = svc.register(TumblingWindow(Time, 500), tenant="acme")
    h_b = svc.register(SlidingWindow(Time, 2000, 500), tenant="beta")
    svc.run(3, collect=False)
    svc.sync()
    tallied = {"acme": 0, "beta": 0, "default": 0}
    by_slot = {h_a.slot: "acme", h_b.slot: "beta"}
    for out in svc.run(4, collect=True):
        rows = svc.results_by_slot(out)
        for slot, slot_rows in rows.items():
            tenant = by_slot.get(slot, "default")
            tallied[tenant] += len(slot_rows)
        svc.account_emissions(rows)
    svc.sync()
    roll = att.rollup()
    for tenant, n in tallied.items():
        assert roll.get(tenant, {}).get("windows", 0) == n, tenant
    assert att.totals()["windows"] == sum(tallied.values())
    assert att.conservation_ok()
    svc.check_overflow()


# ---------------------------------------------------------------------------
# mesh reshard + supervisor crash/restore: the identity survives both
# ---------------------------------------------------------------------------

_CHURN = {1: [("register", SlidingWindow(Time, 2000, 500), "acme")],
          3: [("cancel_one", "acme"),
              ("register", TumblingWindow(Time, 500), "beta")]}
_RESHARD = {2: 4}


def _mesh_env(base_dir, trace_cell):
    from scotty_tpu.delivery import EXACTLY_ONCE, TransactionalSink
    from scotty_tpu.mesh_serving import (
        MeshQueryService,
        run_supervised_mesh,
    )

    obs = Observability(flight=_obs.FlightRecorder(capacity=4096))
    obs.attach_attribution(clock=ManualClock())

    def make_mesh(shards):
        return MeshQueryService(
            [SumAggregation()], slice_grid=500, max_window_size=4000,
            n_keys=16, n_shards=shards, throughput=16_000,
            wm_period_ms=1000, max_lateness=1000, seed=3, config=MESH_CFG,
            admission=QueryAdmission(max_queries=8),
            windows=[TumblingWindow(Time, 1000)], obs=obs,
            trace_cell=trace_cell)

    def run():
        sup = Supervisor(os.path.join(base_dir, "ck"),
                         clock=ManualClock(), obs=obs, max_restarts=8,
                         seed=11)
        sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
        return run_supervised_mesh(
            make_mesh, 5, sup, sink=sink, churn=_CHURN,
            reshard_at=_RESHARD, initial_shards=8, checkpoint_every=2,
            obs=obs)

    return obs, run


def _assert_ledger_equals_counters(obs):
    att = obs.attribution
    snap = obs.snapshot()
    totals = att.totals()
    for fam, counter in (("registered", "serving_registered"),
                         ("cancelled", "serving_cancelled"),
                         ("rejected", "serving_rejected")):
        assert totals[fam] == snap.get(counter, 0), fam
    assert att.conservation_ok()
    return att, totals


def test_mesh_reshard_conserves_and_itemizes_retraces(tmp_path):
    cell = [0]
    obs, run = _mesh_env(str(tmp_path), cell)
    delivered = run()
    assert delivered
    att, totals = _assert_ledger_equals_counters(obs)
    # the 8→4 reshard's forced retrace is itemized, apportioned over
    # the tenants active at the reshard
    assert totals["retraces"] >= 1
    # emissions were accounted per delivered interval: every delivered
    # row has an owning tenant in the ledger
    assert totals["windows"] == sum(
        len(rows) for (_i, _s, _g, rows) in delivered)


def test_crash_restore_replays_ledger_identically(tmp_path):
    """Arm ONE mid-run crash site (the PR 8 chaos plumbing), recover
    under the supervisor, and require the delivered output bit-match
    the uninterrupted oracle AND the attribution identity still hold —
    the restore replays re-register and re-account through the same
    call sites, so ledger == counters even across the crash."""
    from scotty_tpu.resilience.chaos import ArmedFault, CrashPlan

    cell = [0]
    oracle_box = []
    obs, run = _mesh_env(os.path.join(str(tmp_path), "oracle"), cell)
    sites = CrashPlan().record(obs, lambda: oracle_box.extend(run()))
    _assert_ledger_equals_counters(obs)
    oracle = list(oracle_box)
    assert oracle and sites

    emit_sites = [s for s in sites
                  if s.domain == "flight" and s.kind == "emit"]
    assert emit_sites
    site = emit_sites[len(emit_sites) // 2]   # a mid-run emission
    obs2, run2 = _mesh_env(os.path.join(str(tmp_path), "armed"), cell)
    armed = ArmedFault(site, obs2)
    with armed:
        delivered = run2()
    assert armed.fired is not None            # the crash actually hit
    assert list(delivered) == oracle          # exactly-once held
    _assert_ledger_equals_counters(obs2)
    att2 = obs2.attribution
    assert att2.totals()["registered"] \
        >= obs.attribution.totals()["registered"]  # replays re-account
