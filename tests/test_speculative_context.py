"""Speculative chunked batching for the generic-context path (ISSUE 11).

The contract under test: for specs certifying
``DeviceContextSpec.speculation_params``, feeding an OUT-OF-ORDER chunk
through ``TpuWindowOperator`` produces exactly the emissions the
per-tuple arrival-order scan produces — the planner batches only the
segments it can prove, and every segmentation-boundary hazard (exact-gap
orphan collisions, components touching non-top rows, stale-mirror
regions after a fallback, capped order-dependence) must either be
batched correctly or detected and routed to the scan.

Oracles: the scan-only twin (``_ctx_planners`` forced off — the r5
behavior), the tuned session engine (for plain sessions), and the host
simulator through ``GenericSessionWindow``'s reference context.
"""

import numpy as np
import pytest

from scotty_tpu import (
    CappedSessionWindow,
    GenericSessionWindow,
    SessionWindow,
    SlicingWindowOperator,
    SumAggregation,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.engine.context import (
    CappedSessionDecider,
    SessionDecider,
    SpeculationCert,
    SpeculativePlanner,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=512, batch_size=1024, annex_capacity=512,
                   min_trigger_pad=32)


def _drive(window, batches, wms, speculative=True, lateness=10_000,
           config=CFG):
    """Feed arrival-order batches + watermarks; return emissions and the
    operator (for stats). ``speculative=False`` forces the scan-only
    r5 path as the differential baseline."""
    op = TpuWindowOperator(config=config)
    op.add_window_assigner(window)
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(lateness)
    out = []
    for (vals, ts), wm in zip(batches, wms):
        if not op._built:
            op._build()
        if not speculative:
            op._ctx_planners = tuple(None for _ in op._ctx_planners)
        op.process_elements(np.asarray(vals, np.float32),
                            np.asarray(ts, np.int64))
        op._flush()       # each staged batch is its own launch boundary
        if wm is not None:
            for w in op.process_watermark(wm):
                out.append((w.start, w.end,
                            round(float(w.agg_values[0]), 2)
                            if w.has_value() else None))
    op.check_overflow()
    return out, op


def _chaos_batches(seed, n_batches=8, n=300, gap_ms=400, span=280,
                   late_pct=0.25, back=120):
    """Arrival-order chaos: paced bursts separated by silent spans (so
    sessions actually close), a late fraction displaced back by up to
    ``back`` ms (so batches arrive OOO and reach into prior bursts)."""
    rng = np.random.default_rng(seed)
    batches, wms = [], []
    for i in range(n_batches):
        base = i * gap_ms
        ts = np.sort(rng.integers(base, base + span,
                                  size=n)).astype(np.int64)
        late = rng.random(n) < late_pct
        ts = np.where(late,
                      np.maximum(ts - rng.integers(0, back, size=n), 0),
                      ts)
        vals = rng.integers(1, 60, size=n).astype(np.float32)
        batches.append((vals, ts))
        wms.append(base + gap_ms)
    return batches, wms


# ---------------------------------------------------------------------------
# differential: speculative == scan == tuned == simulator
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [1, 7, 23, 41])
def test_speculative_equals_scan_chaos(seed):
    """Chaos OOO streams through GenericSessionWindow: the speculative
    plan must emit exactly what the per-tuple scan emits, while actually
    batching the bulk of the stream."""
    batches, wms = _chaos_batches(seed)
    fast, op_f = _drive(GenericSessionWindow(Time, 60), batches, wms)
    slow, _ = _drive(GenericSessionWindow(Time, 60), batches, wms,
                     speculative=False)
    assert fast == slow
    st = op_f._ctx_spec_stats
    total = st["speculative_tuples"] + st["fallback_tuples"]
    assert total == sum(len(v) for v, _ in batches)
    # the whole point: the fast path carries the bulk of the stream
    # (the occasional wholesale-conservative batch is fine — the gated
    # counters and the recorded cell's fallback rate police the rest)
    assert st["speculative_tuples"] >= 0.7 * total, st


@pytest.mark.parametrize("seed", [3, 19])
def test_speculative_matches_tuned_sessions_and_simulator(seed):
    """GenericSessionWindow ≡ SessionWindow semantics: the generic
    speculative path, the tuned session engine and the host simulator
    agree on chaos OOO streams (the three-way oracle)."""
    batches, wms = _chaos_batches(seed, n_batches=6, n=120)
    fast, _ = _drive(GenericSessionWindow(Time, 60), batches, wms)
    tuned, _ = _drive(SessionWindow(Time, 60), batches, wms)
    assert fast == tuned
    sim = SlicingWindowOperator()
    sim.add_window_assigner(GenericSessionWindow(Time, 60))
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(10_000)
    got = []
    for (vals, ts), wm in zip(batches, wms):
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
        for w in sim.process_watermark(wm):
            got.append((w.get_start(), w.get_end()))
    assert [(s, e) for (s, e, _) in fast] == got


def test_capped_ooo_equals_scan():
    """Capped specs are NOT order-free: internally-OOO components must
    fall back, and the results must still equal the scan twin."""
    batches, wms = _chaos_batches(11, n_batches=6, n=150)
    fast, op_f = _drive(CappedSessionWindow(Time, 60, 200), batches, wms)
    slow, _ = _drive(CappedSessionWindow(Time, 60, 200), batches, wms,
                     speculative=False)
    assert fast == slow
    assert op_f._ctx_spec_stats["fallback_runs"] > 0


def test_capped_sorted_components_batch():
    """OOO only ACROSS isolated components, sorted within: capped specs
    may batch those (the certified chain on each stretch)."""
    # two bursts > gap apart, delivered burst-2-first (arrival OOO),
    # each internally sorted
    b1 = (np.arange(10, dtype=np.float32) + 1,
          np.arange(1000, 1100, 10, dtype=np.int64))
    b2 = (np.arange(10, dtype=np.float32) + 1,
          np.arange(2000, 2100, 10, dtype=np.int64))
    vals = np.concatenate([b2[0], b1[0]])
    ts = np.concatenate([b2[1], b1[1]])
    fast, op_f = _drive(CappedSessionWindow(Time, 60, 500),
                        [(vals, ts)], [4000])
    slow, _ = _drive(CappedSessionWindow(Time, 60, 500),
                     [(vals, ts)], [4000], speculative=False)
    assert fast == slow and len(fast) == 2
    st = op_f._ctx_spec_stats
    assert st["speculative_tuples"] == 20 and st["fallback_tuples"] == 0


# ---------------------------------------------------------------------------
# segmentation boundary cases
# ---------------------------------------------------------------------------


def test_exact_gap_orphan_hazard_detected():
    """The exact-gap start-side collision: a tuple whose only reach is a
    row starting exactly ``gap`` later, with the row's seed arriving
    FIRST, orphans under arrival order but would merge under sorted
    order — the planner must detect it and fall back, keeping the
    scan's (reference) semantics."""
    g = 50
    # arrival: 400 first, then 350 (== 400 - g, exact), isolated pair
    vals = np.asarray([1.0, 2.0], np.float32)
    ts = np.asarray([400, 350], np.int64)
    fast, op_f = _drive(GenericSessionWindow(Time, g),
                        [(vals, ts)], [1000])
    slow, _ = _drive(GenericSessionWindow(Time, g),
                     [(vals, ts)], [1000], speculative=False)
    assert fast == slow
    # arrival-order semantics: 350 orphans, window [400, 450) sums 1.0
    assert fast == [(400, 450, 1.0)]
    assert op_f._ctx_spec_stats["fallback_tuples"] == 2


def test_exact_gap_with_in_reach_precedent_batches():
    """Same exact-gap pair, but an in-reach tuple precedes the exposed
    one — no orphan is possible, so the planner may batch, and sorted
    application matches arrival order."""
    g = 50
    vals = np.asarray([1.0, 4.0, 2.0], np.float32)
    ts = np.asarray([400, 380, 350], np.int64)   # 380 precedes 350
    fast, op_f = _drive(GenericSessionWindow(Time, g),
                        [(vals, ts)], [1000])
    slow, _ = _drive(GenericSessionWindow(Time, g),
                     [(vals, ts)], [1000], speculative=False)
    assert fast == slow == [(350, 450, 7.0)]
    assert op_f._ctx_spec_stats["fallback_tuples"] == 0


def test_component_touching_non_top_row_falls_back():
    """A late component landing in reach of a NON-top live row cannot
    take the chunk kernel (it only continues the top row): planner must
    scan it, and results must match the scan twin."""
    g = 60
    b1 = (np.full(5, 1.0, np.float32),
          np.asarray([1000, 1010, 1020, 1030, 1040], np.int64))
    b2 = (np.full(5, 1.0, np.float32),
          np.asarray([2000, 2010, 2020, 2030, 2040], np.int64))
    # late burst extending the FIRST (now non-top) session's end
    b3 = (np.full(3, 1.0, np.float32),
          np.asarray([1080, 1090, 1100], np.int64))
    batches = [b1, b2, (np.concatenate([b2[0], b3[0]]),
                        np.concatenate([b2[1] + 500, b3[1]]))]
    wms = [None, None, 5000]
    fast, op_f = _drive(GenericSessionWindow(Time, g), batches, wms)
    slow, _ = _drive(GenericSessionWindow(Time, g), batches, wms,
                     speculative=False)
    assert fast == slow
    assert op_f._ctx_spec_stats["fallback_tuples"] >= 3


def test_two_components_through_wide_top_row_fall_back():
    """Two sorted components more than ``gap`` apart can still interact
    THROUGH a wide live top row (both fold inside it): the planner must
    not batch either."""
    g = 30
    # a wide session [1000, 1500] built in-order
    b1_ts = np.arange(1000, 1501, 25, dtype=np.int64)
    b1 = (np.full(b1_ts.size, 1.0, np.float32), b1_ts)
    # OOO chunk: two inside-the-span bursts > gap apart
    b2 = (np.asarray([2.0, 2.0, 3.0, 3.0], np.float32),
          np.asarray([1300, 1310, 1100, 1110], np.int64))
    batches, wms = [b1, b2], [None, 2000]
    fast, op_f = _drive(GenericSessionWindow(Time, g), batches, wms)
    slow, _ = _drive(GenericSessionWindow(Time, g), batches, wms,
                     speculative=False)
    assert fast == slow and len(fast) == 1
    assert op_f._ctx_spec_stats["fallback_tuples"] >= 4


def test_stale_mirror_recovers_after_fallback():
    """After a scan fallback the bounds mirror goes stale below U; later
    in-order traffic keeps batching above it, and once the watermark
    passes U + reach the stale region clears (speculation resumes for
    everything)."""
    g = 50
    win = GenericSessionWindow(Time, g)
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(win)
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    # exact-gap hazard pair → fallback → stale region
    op.process_elements(np.asarray([1.0, 1.0], np.float32),
                        np.asarray([400, 350], np.int64))
    op._flush()
    pl = op._ctx_planners[0]
    assert pl.stale_u is not None
    # far-above in-order traffic still batches
    ts = np.arange(2000, 2400, 10, dtype=np.int64)
    op.process_elements(np.full(ts.size, 1.0, np.float32), ts)
    op._flush()
    assert op._ctx_spec_stats["speculative_tuples"] == ts.size
    # watermark past U + reach clears the stale region
    op.process_watermark(3000)
    assert pl.stale_u is None
    op.check_overflow()


def test_device_ingest_invalidates_mirror():
    """Device-resident chunks are host-opaque: the planner mirror must
    go conservatively unknown, and later host OOO chunks must still be
    correct (falling back under the stale region)."""
    import jax

    op = TpuWindowOperator(config=EngineConfig(
        capacity=512, batch_size=64, annex_capacity=512,
        min_trigger_pad=32))
    op.add_window_assigner(CappedSessionWindow(Time, 50, 10_000))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    ts = np.arange(0, 640, 10, dtype=np.int64)
    op.ingest_device_batch(jax.device_put(np.ones(64, np.float32)),
                           jax.device_put(ts), 0, 630)
    assert op._ctx_planners[0].stale_u is not None
    # host OOO chunk below the unknown region → scan, still correct
    op.process_elements(np.asarray([5.0, 5.0], np.float32),
                        np.asarray([700, 650], np.int64))
    op._flush()
    out = [(w.start, w.end, float(w.agg_values[0]))
           for w in op.process_watermark(2000) if w.has_value()]
    op.check_overflow()
    assert out == [(0, 750, 74.0)]


def test_checkpoint_restore_invalidates_mirror(tmp_path):
    """A restore rewinds host clocks under the mirror: every planner
    must go conservatively unknown (restored row bounds are opaque)."""
    from scotty_tpu.utils.checkpoint import (restore_engine_operator,
                                             save_engine_operator)

    def mk():
        op = TpuWindowOperator(config=CFG)
        op.add_window_assigner(GenericSessionWindow(Time, 50))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(10_000)
        return op

    op = mk()
    ts = np.arange(0, 400, 10, dtype=np.int64)
    op.process_elements(np.full(ts.size, 1.0, np.float32), ts)
    # context states are host-opaque to the snapshot: the restored
    # twin's planner must not trust a mirror it never rebuilt
    save_engine_operator(op, str(tmp_path / "ck"))
    twin = mk()
    twin.process_element(1.0, 5)          # build
    restore_engine_operator(twin, str(tmp_path / "ck"))
    assert twin._ctx_planners[0].stale_u is not None


# ---------------------------------------------------------------------------
# planner unit behavior
# ---------------------------------------------------------------------------


def test_speculative_counters_gated():
    """The fallback counters are wired into the obs-diff default gate
    (a silent regression to the per-tuple scan must fail `obs diff`)."""
    from scotty_tpu import obs as _obs
    from scotty_tpu.obs.diff import DEFAULT_THRESHOLDS

    m = DEFAULT_THRESHOLDS["metrics"]
    assert _obs.CTX_SPECULATIVE_FALLBACK_TUPLES in m
    assert _obs.CTX_SPECULATIVE_FALLBACKS in m
    assert m[_obs.CTX_SPECULATIVE_FALLBACK_TUPLES]["default"] == 0


def test_planner_requires_certifications():
    class NoCert(SessionDecider):
        def speculation_params(self):
            return None

    with pytest.raises(ValueError):
        SpeculativePlanner(NoCert(10))

    class BadReach(SessionDecider):
        def speculation_params(self):
            return SpeculationCert(reach=self.gap + 1, order_free=True)

    with pytest.raises(ValueError):
        SpeculativePlanner(BadReach(10))


def test_planner_component_cuts_and_coalescing():
    pl = SpeculativePlanner(SessionDecider(10))
    # three isolated components, all safe → ONE coalesced chunk run
    ts = np.asarray([100, 105, 300, 305, 500, 505], np.int64)
    runs = pl.plan(ts)
    assert [k for k, _ in runs] == ["chunk"]
    assert runs[0][1].size == 6
    pl.note_chunk(ts)
    np.testing.assert_array_equal(pl.first, [100, 300, 500])
    np.testing.assert_array_equal(pl.last, [105, 305, 505])
    # sweep prunes by the certified trigger rule (last + reach < wm)
    pl.sweep(320)
    np.testing.assert_array_equal(pl.first, [500])


def test_planner_capped_mirror_tracks_cap_splits():
    """The host chain walk must mirror the device kernel's span-cap
    splits (anchor + cap searchsorted)."""
    pl = SpeculativePlanner(CappedSessionDecider(10, 25))
    ts = np.arange(0, 60, 5, dtype=np.int64)      # one dense run, span 55
    pl.note_chunk(ts)
    # chain: [0,25] (cap), [30,55] — splits at anchor+cap boundaries
    np.testing.assert_array_equal(pl.first, [0, 30])
    np.testing.assert_array_equal(pl.last, [25, 55])


def test_planner_scan_staleness_bounds():
    pl = SpeculativePlanner(SessionDecider(10))
    pl.note_chunk(np.asarray([100, 200, 300], np.int64))
    pl.note_scan(np.asarray([205], np.int64))      # V = 215: row 300 known
    np.testing.assert_array_equal(pl.first, [300])
    assert pl.stale_u == 205
    # component just above U but within reach → unsafe
    runs = pl.plan(np.asarray([212, 214], np.int64))
    assert [k for k, _ in runs] == ["scan"]
    # component beyond U + reach and inside the known top → safe
    runs = pl.plan(np.asarray([301, 300], np.int64))
    assert [k for k, _ in runs] == ["chunk"]
