"""Interpreter-mode differential suite for the Pallas hot-path kernels
(ISSUE 15 tentpole).

Every kernel runs here under Pallas interpreter mode (the CPU backend
resolution — ``scotty_tpu.pallas.resolve_interpret``) and is held, over
a chaos-seeded out-of-order corpus, against BOTH its XLA twin and a
host (numpy) oracle:

* sort-split: bit-match lane for lane (the bitonic (bucket, lane)
  network order IS the stable-sort order);
* segmented folds: bit-match in the float-exact regime (integer-valued
  f32 lanes with bounded sums — the chaos-suite discipline), and the
  bf16 ``packed`` arm bounded by the DERIVED tolerance
  (``pallas.packed_tolerance``), asserted as-is;
* the flagged-on pipelines (aligned / keyed / dense-ingest operator)
  bit-match their flags-off twins in the exact regime (power-of-two
  value scale, lane counts whose sums stay exactly representable);
* fallback arms: a batch span over the 31-bit bucket budget and a
  non-power-of-two batch size each route to the XLA twin, counted as
  ``pallas_fallbacks`` — never silent.
"""

import numpy as np
import pytest

import scotty_tpu.obs as obs_mod
from scotty_tpu import (
    MaxAggregation,
    MinAggregation,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.engine.config import EngineConfig as _EC  # noqa: F401
from scotty_tpu.shaper import ShaperConfig, StreamShaper
from scotty_tpu.shaper import device as shdev

Time = WindowMeasure.Time


def _leaves_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ---------------------------------------------------------------------------
# sort-split: pallas vs XLA twin vs host oracle over the chaos OOO corpus
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [0, 1, 2, 7])
def test_sort_split_differential_chaos(seed):
    import jax

    from scotty_tpu import pallas as spl

    rng = np.random.default_rng(seed)
    B, L = 128, 32
    lo = int(rng.integers(0, 1000))
    span = int(rng.integers(10, 4000))
    ts = rng.integers(lo, lo + span, size=B).astype(np.int64)
    # duplicates on purpose: stability is part of the contract
    ts[rng.random(B) < 0.3] = lo + int(rng.integers(0, span))
    vals = rng.random(B).astype(np.float32)
    valid = rng.random(B) < 0.85
    cut = np.int64(lo + span // 3)
    seed_met = cut

    xla = jax.jit(shdev.build_sort_split(B, L), donate_argnums=0)
    pls = jax.jit(spl.build_pallas_sort_split(B, L), donate_argnums=0)
    out_x = xla(shdev.init_shaper_stats(), ts, vals, valid, cut, seed_met)
    out_p = pls(shdev.init_shaper_stats(), ts, vals, valid, cut, seed_met,
                np.int64(lo))
    _leaves_equal(out_x, out_p)

    # host oracle: stable argsort of the sentinel-masked key
    key = np.where(valid, ts, np.int64(shdev.TS_SENTINEL))
    order = np.argsort(key, kind="stable")
    sort_ts, sort_vals = key[order], vals[order]
    n_valid = int(valid.sum())
    n_late = min(int(np.searchsorted(sort_ts, cut, side="left")), n_valid)
    (_, io_ts, io_vals, io_valid, l_ts, l_vals, l_valid) = [
        np.asarray(x) for x in out_p]
    assert int(np.asarray(io_valid).sum()) == n_valid - n_late
    assert int(np.asarray(l_valid).sum()) == n_late
    np.testing.assert_array_equal(
        io_ts[:n_valid - n_late], sort_ts[n_late:n_valid])
    np.testing.assert_array_equal(
        io_vals[:n_valid - n_late], sort_vals[n_late:n_valid])
    np.testing.assert_array_equal(l_ts[:n_late], sort_ts[:n_late])
    np.testing.assert_array_equal(l_vals[:n_late], sort_vals[:n_late])


def test_sort_split_rejects_non_power_of_two():
    from scotty_tpu import pallas as spl

    with pytest.raises(ValueError):
        spl.build_pallas_sort_split(100, 16)


def test_sort_span_budget():
    from scotty_tpu import pallas as spl

    assert spl.sort_span_fits(0)
    assert spl.sort_span_fits((1 << 31) - 3)
    assert not spl.sort_span_fits(1 << 31)
    assert not spl.sort_span_fits(-1)


# ---------------------------------------------------------------------------
# segmented folds: pallas vs XLA twin vs numpy oracle
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["sum", "min", "max"])
def test_row_fold_differential(kind):
    import jax
    import jax.numpy as jnp

    from scotty_tpu import pallas as spl

    rng = np.random.default_rng(3)
    rows, lanes, W = 16, 48, 3
    lifted = rng.integers(0, 16, size=(rows * lanes, W)).astype(np.float32)
    ident = {"sum": 0.0, "min": np.float32(np.finfo(np.float32).max),
             "max": np.float32(-np.finfo(np.float32).max)}[kind]
    red = {"sum": np.sum, "min": np.min, "max": np.max}[kind]
    oracle = red(lifted.reshape(rows, lanes, W).astype(np.float64), axis=1)
    twin = np.asarray(jax.device_get({"sum": jnp.sum, "min": jnp.min,
                                      "max": jnp.max}[kind](
        jnp.asarray(lifted).reshape(rows, lanes, W), axis=1)))
    got = np.asarray(jax.jit(lambda v: spl.row_fold(
        v, rows, lanes, kind, identity=ident))(lifted))
    np.testing.assert_array_equal(got, twin)          # XLA twin
    np.testing.assert_array_equal(got, oracle)        # host oracle (exact)


def test_row_fold_packed_bf16_tolerance_derived():
    import jax

    from scotty_tpu import pallas as spl

    rng = np.random.default_rng(11)
    rows, lanes, W = 8, 64, 2
    lifted = (rng.random((rows * lanes, W)).astype(np.float32) * 100.0)
    exact = np.sum(lifted.reshape(rows, lanes, W).astype(np.float64),
                   axis=1)
    got = np.asarray(jax.jit(lambda v: spl.row_fold(
        v, rows, lanes, "sum", identity=0.0, packed=True))(lifted))
    tol = spl.packed_tolerance(lanes, float(np.abs(lifted).max()), "sum")
    err = float(np.abs(got - exact).max())
    assert err <= tol, (err, tol)
    # the derived bound is TIGHT enough to mean something: a full f32
    # bit-match would make the packed arm pointless to tolerate
    assert tol < float(np.abs(exact).max())


@pytest.mark.parametrize("cells", [1, 3])
def test_sparse_fold_differential(cells):
    import jax

    from scotty_tpu import pallas as spl

    rng = np.random.default_rng(5)
    rows, lanes, width = 6, 32, 24
    N = rows * lanes
    col = rng.integers(0, width, size=(cells, N)).astype(np.int32)
    val = rng.integers(0, 9, size=(cells, N)).astype(np.float32)
    oracle = np.zeros((rows, width), np.float64)
    for d in range(cells):
        for i in range(N):
            oracle[i // lanes, col[d, i]] += val[d, i]
    c_in = col[0] if cells == 1 else col
    v_in = val[0] if cells == 1 else val
    got = np.asarray(jax.jit(lambda c, v: spl.sparse_row_fold(
        c, v, rows, lanes, width, "sum", 0.0))(c_in, v_in))
    np.testing.assert_array_equal(got, oracle)


def test_segment_fold_differential_variable_runs():
    import jax

    from scotty_tpu import pallas as spl

    rng = np.random.default_rng(9)
    B, R, W = 192, 8, 2
    # sorted run ids with empty runs and an invalid tail aliasing the
    # last run with identity values (the _lift mask contract)
    k = np.sort(rng.choice([0, 1, 3, 4, 7], size=B)).astype(np.int32)
    lifted = rng.integers(0, 7, size=(B, W)).astype(np.float32)
    lifted[-10:] = 0.0                     # identity-masked invalid lanes
    fold = spl.build_segment_fold(B, R, W, "sum", identity=0.0)
    got = np.asarray(jax.jit(fold)(k, lifted))
    oracle = np.zeros((R, W), np.float64)
    for i in range(B):
        oracle[k[i]] += lifted[i]
    np.testing.assert_array_equal(got, oracle)


# ---------------------------------------------------------------------------
# flagged-on pipelines bit-match their flags-off twins (exact regime)
# ---------------------------------------------------------------------------


def _aligned(**flags):
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    return AlignedStreamPipeline(
        [SlidingWindow(Time, 400, 100)],
        [SumAggregation(), MinAggregation(), MaxAggregation()],
        config=EngineConfig(capacity=1 << 12, annex_capacity=256,
                            min_trigger_pad=32, **flags),
        throughput=2560, wm_period_ms=200, max_lateness=200, seed=3,
        gc_every=10 ** 9, value_scale=8.0)


def test_aligned_pallas_fold_bit_matches_flags_off():
    import jax

    p_off = _aligned()
    r_off = [jax.device_get(r) for r in p_off.run(4)]
    p_off.sync()
    p_on = _aligned(pallas_slice_merge=True)
    r_on = [jax.device_get(r) for r in p_on.run(4)]
    p_on.sync()
    _leaves_equal(r_off, r_on)
    p_on.check_overflow()


def test_keyed_pallas_fold_bit_matches_flags_off():
    import jax

    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    def mk(**flags):
        return KeyedAlignedPipeline(
            [TumblingWindow(Time, 100)],
            [SumAggregation(), MinAggregation()],
            n_keys=4,
            config=EngineConfig(capacity=1 << 10, annex_capacity=32,
                                min_trigger_pad=32, **flags),
            throughput=4 * 64 * 10, wm_period_ms=200, max_lateness=200,
            seed=1, gc_every=10 ** 9, value_scale=4.0)

    a = mk()
    ra = [jax.device_get(r) for r in a.run(3)]
    a.sync()
    b = mk(pallas_slice_merge=True)
    rb = [jax.device_get(r) for r in b.run(3)]
    b.sync()
    _leaves_equal(ra, rb)
    assert b._pallas_in_step


def test_mesh_pallas_fold_bit_matches_flags_off():
    import jax

    from scotty_tpu.mesh import MeshKeyedPipeline

    def mk(**flags):
        return MeshKeyedPipeline(
            [TumblingWindow(Time, 100)], [SumAggregation()],
            n_keys=16, n_shards=8,
            config=EngineConfig(capacity=1 << 10, batch_size=32,
                                annex_capacity=32, min_trigger_pad=32,
                                **flags),
            throughput=16 * 40, wm_period_ms=200, max_lateness=200,
            seed=5, gc_every=10 ** 9, value_scale=4.0)

    a = mk()
    ra = [jax.device_get(r) for r in a.run(3)]
    a.sync()
    b = mk(pallas_slice_merge=True)
    rb = [jax.device_get(r) for r in b.run(3)]
    b.sync()
    _leaves_equal(ra, rb)


def _run_shaped_stream(pallas: bool, obs=None, n_batches=6, back=200):
    """A chaos OOO device stream through StreamShaper → operator →
    watermark emissions; returns the emitted window rows."""
    B = 256
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, annex_capacity=256, batch_size=B,
        min_trigger_pad=32, pallas_sort_split=pallas))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(600)
    if obs is not None:
        op.set_observability(obs)
    sh = StreamShaper(op, ShaperConfig(late_capacity=160), obs=obs)
    rng = np.random.default_rng(7)
    out = []
    for i in range(n_batches):
        lo, hi = i * 500, (i + 1) * 500
        ts = rng.integers(max(0, lo - back), hi, size=B).astype(np.int64)
        vals = rng.integers(0, 7, size=B).astype(np.float32)
        sh.shape_device_batch(vals, ts, max(0, lo - back), hi)
        if i >= 2:
            out += [(w.start, w.end, tuple(map(float, w.agg_values)))
                    for w in op.process_watermark(hi - 300)
                    if w.has_value()]
    sh.check()
    op.check_overflow()
    return out


def test_shaper_pallas_end_to_end_bit_match_and_counts():
    o = obs_mod.Observability()
    base = _run_shaped_stream(False)
    flagged = _run_shaped_stream(True, obs=o)
    assert base == flagged and len(base) > 0
    snap = o.snapshot()
    assert snap.get("pallas_kernel_dispatches", 0) >= 6
    assert "pallas_fallbacks" not in snap or snap["pallas_fallbacks"] == 0


def test_shaper_pallas_span_fallback_counted():
    """A batch whose host-known span overflows the 31-bit bucket budget
    must fall back to the XLA twin — counted, results identical."""
    B = 128
    o = obs_mod.Observability()
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, annex_capacity=128, batch_size=B,
        min_trigger_pad=32, pallas_sort_split=True))
    op.add_window_assigner(TumblingWindow(Time, 1 << 32))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1 << 33)
    op.set_observability(o)
    sh = StreamShaper(op, ShaperConfig(late_capacity=64), obs=o)
    rng = np.random.default_rng(0)
    hi = (1 << 31) + 10_000                # span > 2^31: budget miss
    ts = rng.integers(0, hi, size=B).astype(np.int64)
    sh.shape_device_batch(rng.random(B).astype(np.float32), ts, 0, hi)
    sh.check()
    op.check_overflow()
    snap = o.snapshot()
    assert snap.get("pallas_fallbacks", 0) == 1
    assert snap.get("pallas_kernel_dispatches", 0) in (0, None) or \
        snap.get("pallas_kernel_dispatches", 0) == 0


def test_shaper_pallas_shape_fallback_disables_once():
    """A non-power-of-two batch size is a build-time property: ONE
    counted fallback, then the shaper stays on the XLA twin."""
    B = 192                                 # not a power of two
    o = obs_mod.Observability()
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, annex_capacity=128, batch_size=B,
        min_trigger_pad=32, pallas_sort_split=True))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(600)
    op.set_observability(o)
    sh = StreamShaper(op, ShaperConfig(late_capacity=64), obs=o)
    rng = np.random.default_rng(0)
    for i in range(3):
        lo, hi = i * 500, (i + 1) * 500
        ts = rng.integers(lo, hi, size=B).astype(np.int64)
        sh.shape_device_batch(rng.random(B).astype(np.float32), ts, lo, hi)
    sh.check()
    op.check_overflow()
    snap = o.snapshot()
    assert snap.get("pallas_fallbacks", 0) == 1
    assert not sh._pallas_sort


def test_dense_ingest_pallas_fold_bit_match():
    """The operator's scatter-free dense kernel with the Pallas segment
    fold bit-matches the XLA twin over an in-order stream."""
    def run(flag):
        B = 256
        op = TpuWindowOperator(config=EngineConfig(
            capacity=1 << 10, annex_capacity=64, batch_size=B,
            min_trigger_pad=32, pallas_slice_merge=flag))
        op.add_window_assigner(TumblingWindow(Time, 100))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(100)
        rng = np.random.default_rng(2)
        out = []
        for i in range(4):
            lo, hi = i * 500, (i + 1) * 500
            ts = np.sort(rng.integers(lo, hi, size=B)).astype(np.int64)
            vals = rng.integers(0, 9, size=B).astype(np.float32)
            op.process_elements(vals, ts)
            if i >= 1:
                out += [(w.start, w.end, tuple(map(float, w.agg_values)))
                        for w in op.process_watermark(hi - 100)
                        if w.has_value()]
        op.check_overflow()
        return out

    base, flagged = run(False), run(True)
    assert base == flagged and len(base) > 0


def test_interpret_mode_context():
    from scotty_tpu import pallas as spl

    assert spl.resolve_interpret(True) is True
    assert spl.resolve_interpret(False) is False
    before = spl.resolve_interpret(None)
    with spl.interpret_mode(True):
        assert spl.resolve_interpret(None) is True
        with spl.interpret_mode(False):
            assert spl.resolve_interpret(None) is False
        assert spl.resolve_interpret(None) is True
    assert spl.resolve_interpret(None) == before
