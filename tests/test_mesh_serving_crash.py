"""Crash-point sweep over the reshard commit path (ISSUE 13 satellite):
the PR 8 fuzzer discipline applied to the mesh-serving supervised loop —
arm a fault at EVERY instrumented site (each flight-event emit point:
registers, cancels, sink emissions, epoch commits, the mesh_reshard
event itself; plus every fsio write/fsync/replace inside checkpoint →
reshard → restore, with torn/short/ENOSPC variants), crash a fresh run
there, recover under the Supervisor — rebuilding AT THE SHARD COUNT
SCHEDULED FOR THE RESUME INTERVAL, the restore-at-M path — and require
the delivered output bit-match the uninterrupted oracle with no
duplicate ``(epoch, seq)`` tags (the loop's deliver hook raises on any
tag seen twice, so a duplicate fails the armed run itself)."""

import os

import pytest

from scotty_tpu import (SlidingWindow, SumAggregation, TumblingWindow,
                        WindowMeasure)
from scotty_tpu import obs as _obs
from scotty_tpu.delivery import EXACTLY_ONCE, TransactionalSink
from scotty_tpu.engine import EngineConfig
from scotty_tpu.mesh_serving import MeshQueryService, run_supervised_mesh
from scotty_tpu.resilience import ManualClock, Supervisor
from scotty_tpu.resilience.chaos import CrashPlan, crash_point_sweep
from scotty_tpu.serving import QueryAdmission

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=64, annex_capacity=8, min_trigger_pad=32)

#: one shared trace cell across every per-site environment: the sweep
#: builds a fresh service per armed run, and sharing the cell shares the
#: warm step executables (the cell's identity keys the step cache) — the
#: sweep certifies delivery, not retrace accounting
_CELL = [0]

#: churn + reshard plan: registers before the first commit, a cancel+
#: re-register straddling the reshard, 8→4 at interval 1 — so the swept
#: sites cover churned-table commits, the reshard commit itself, and
#: post-reshard emissions
_CHURN = {0: [("register", SlidingWindow(Time, 2000, 500), "acme")],
          2: [("cancel_one", "acme"),
              ("register", TumblingWindow(Time, 500), "beta")]}
_RESHARD = {1: 4}
_N = 3


def _make_env_factory(tmp_path):
    counter = [0]

    def make_env():
        counter[0] += 1
        d = os.path.join(str(tmp_path), f"env{counter[0]}")
        os.makedirs(d, exist_ok=True)
        obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=4096))

        def make_service(shards):
            return MeshQueryService(
                [SumAggregation()], slice_grid=500, max_window_size=4000,
                n_keys=16, n_shards=shards, throughput=16_000,
                wm_period_ms=1000, max_lateness=1000, seed=3, config=CFG,
                admission=QueryAdmission(max_queries=8),
                windows=[TumblingWindow(Time, 1000)], obs=obs,
                trace_cell=_CELL)

        def run():
            sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                             obs=obs, max_restarts=8, seed=11)
            sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
            return run_supervised_mesh(
                make_service, _N, sup, sink=sink, churn=_CHURN,
                reshard_at=_RESHARD, initial_shards=8,
                checkpoint_every=2)

        return obs, run

    return make_env


def _assert_green(report, min_sites):
    assert report.sites >= min_sites
    assert report.fired == report.ran
    assert report.oracle_len > 0
    assert report.failures == [], (
        f"{len(report.failures)} of {report.ran} crash sites broke "
        f"exactly-once delivery across the reshard commit path — "
        f"first: {report.failures[0]}")


def test_enumeration_covers_reshard_commit_sites(tmp_path):
    """The site list spans the whole reshard story: the mesh_reshard
    flight event, the shard-aware query control events, sink emissions,
    and every committed byte of the bundle (state npz, routing sidecar,
    query table, ledger, manifest, pointer) with fault variants."""
    make_env = _make_env_factory(tmp_path)
    obs, run = make_env()
    sites = CrashPlan().record(obs, run)
    assert len(sites) >= 60
    flight_kinds = {s.kind for s in sites if s.domain == "flight"}
    assert {"mesh_reshard", "mesh_query_register", "emit",
            "epoch_commit", "checkpoint"} <= flight_kinds
    fs_names = {s.name for s in sites if s.domain == "fs"}
    assert "mesh_state.npz" in fs_names
    assert "routing.json" in fs_names
    assert "MANIFEST.json" in fs_names
    assert "ledger.json" in fs_names
    assert any(n.startswith("query_table.json") for n in fs_names)
    fs_faults = {s.fault for s in sites
                 if s.domain == "fs" and s.kind == "write"}
    assert fs_faults == {"crash", "torn", "short", "enospc"}


def test_reshard_commit_path_every_site_exactly_once(tmp_path):
    """The headline sweep: EVERY enumerated site across checkpoint →
    reshard → restore-at-M-shards, recovered output bit-identical to
    the uninterrupted oracle, zero duplicate (epoch, seq) tags."""
    report = crash_point_sweep(_make_env_factory(tmp_path))
    _assert_green(report, min_sites=60)
