"""Tier-1 HLO pinning (ISSUE 9 satellite): the canonical fused-step
lowerings hash to the values recorded in tests/hlo_pins.json.

This replaces the manual per-PR "aligned-step HLO hash byte-identical"
ritual (hand-run since ISSUE 1; the pinned aligned hash 19fd4d91… is
the exact value ISSUE 8 recorded, carried forward unchanged by
reproducing its construction byte-for-byte in
scotty_tpu.analysis.hlo). A red test here means the jitted step's HLO
drifted: if deliberate, run ``python -m scotty_tpu.analysis pin-hlo
--update`` and let review see the hash diff; if not, find the
instrumentation/refactor that leaked into the traced path.
"""

import pytest

from scotty_tpu.analysis import hlo


@pytest.fixture(scope="module")
def pins():
    # loaded inside the fixture (not at import) so a missing/corrupt
    # pins file fails with the actionable message, not a collection
    # error that hides it
    try:
        return hlo.load_pins()
    except (OSError, ValueError) as e:
        pytest.fail(f"cannot load tests/hlo_pins.json ({e}) — run "
                    "python -m scotty_tpu.analysis pin-hlo --update")


@pytest.mark.parametrize("name", sorted(hlo.CANONICAL_STEPS))
def test_step_lowering_matches_pin(name, pins):
    assert name in pins, (
        f"no pin recorded for canonical step {name!r} — run "
        "python -m scotty_tpu.analysis pin-hlo --update")
    got = hlo.step_hash(name)
    assert got == pins[name], (
        f"{name} step HLO drifted: {got} != pinned {pins[name]} — "
        "deliberate? pin-hlo --update; accidental? something leaked "
        "into the jitted path")


def test_mutated_config_fails_the_pin(pins):
    """The pin actually discriminates: a deliberately mutated step
    config (tumbling 100 ms instead of the canonical 50 ms) must lower
    to different HLO — otherwise a green pin test proves nothing."""
    mutated = hlo.lowered_hash(
        hlo.CANONICAL_STEPS["aligned"](window_ms=100))
    assert mutated != pins["aligned"]
