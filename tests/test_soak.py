"""Soak harness tests (ISSUE 7): the deterministic ManualClock smoke
soak that rides tier-1, plus the detection paths — a harness that can
only pass is not evidence, so every invariant's FAILURE mode is
exercised too (injected leak, silently-lost tuple, backward watermark),
along with supervised crash recovery and the artifact bundle contract.
"""

import json
import os

import pytest

from scotty_tpu.ingest import RingConfig
from scotty_tpu.obs import FlightRecorder, Observability
from scotty_tpu.resilience.clock import ManualClock
from scotty_tpu.soak import (
    ChaosMix,
    ConnectorSoakTarget,
    SoakConfig,
    SoakRunner,
    check_memory_ratchet,
    check_ring_bounded,
    check_watermark_monotone,
)


def _smoke_config(**kw):
    base = dict(
        duration_s=60.0, offered_rate=1500.0, chunk_records=250,
        audit_every_s=5.0, seed=7,
        chaos=ChaosMix(late_storm_every=7, poison_pct=0.02,
                       flaky_every=11),
        ring=RingConfig(depth=4, block_size=128))
    base.update(kw)
    return SoakConfig(**base)


@pytest.mark.soak
def test_smoke_soak_manualclock_60s_chaos_mix(tmp_path):
    """THE CI smoke soak (acceptance criterion): 60 virtual seconds of
    sustained offered load with late storms, poison and a flaky source
    mixed in — zero invariant failures, exact tuple conservation at
    every audit, /healthz green throughout, artifacts written on
    success."""
    d = str(tmp_path / "soak")
    runner = SoakRunner(_smoke_config(), clock=ManualClock(),
                        report_dir=d)
    report = runner.run()
    assert report["passed"]
    assert report["findings"] == []
    assert report["seen"] == 90_000      # 60 s x 1500/s, deterministic
    assert len(report["audits"]) >= 12
    for row in report["audits"]:
        t = row["terms"]
        # the conservation identity, exact, at EVERY audit
        assert t["seen"] == (t["delivered"] + t["shed"] + t["held"]
                             + t["dead_lettered"] + t["abandoned"])
        assert row["findings"] == []
    # chaos actually happened — this was not a quiet stream
    counters = report["counters"]
    assert counters["resilience_poison_records"] > 0
    assert counters["resilience_source_retries"] > 0
    assert report["audits"][-1]["terms"]["dead_lettered"] > 0
    # /healthz polled throughout, green
    assert len(report["healthz"]) == len(report["audits"])
    assert all(h["status"] == 200 for h in report["healthz"])
    # artifacts exist EVEN ON SUCCESS, well-formed
    with open(os.path.join(d, "soak_report.json")) as f:
        on_disk = json.load(f)
    assert on_disk["schema"] == "scotty_tpu.soak_report/1"
    assert on_disk["passed"] is True
    with open(os.path.join(d, "flight.json")) as f:
        flight = json.load(f)
    assert flight["schema"].startswith("scotty_tpu.flight/")
    kinds = {e["kind"] for e in flight["events"]}
    assert "soak_audit" in kinds


@pytest.mark.soak
def test_soak_determinism_same_seed_same_report(tmp_path):
    cfg = _smoke_config(duration_s=20.0)
    r1 = SoakRunner(cfg, clock=ManualClock(),
                    report_dir=str(tmp_path / "a")).run()
    r2 = SoakRunner(cfg, clock=ManualClock(),
                    report_dir=str(tmp_path / "b")).run()
    assert r1["seen"] == r2["seen"]
    assert [a["terms"] for a in r1["audits"]] \
        == [a["terms"] for a in r2["audits"]]


@pytest.mark.soak
def test_soak_detects_injected_memory_ratchet(tmp_path):
    """A target that leaks must FAIL the soak with a memory_ratchet
    finding naming the trend — tight slacks + a deliberate per-audit
    allocation drive the detector."""
    leak = []
    d = str(tmp_path / "soak")

    def grow(runner, row):
        # lists are ALWAYS gc-tracked (dicts/tuples get untracked when
        # they hold no containers) — visible to live_objects()
        leak.append([[] for _ in range(20_000)])

    runner = SoakRunner(
        _smoke_config(duration_s=60.0, chaos=ChaosMix(),
                      mem_grace_audits=1, mem_ratchet_audits=3,
                      objects_slack=1000, rss_slack_mb=1e9),
        clock=ManualClock(), report_dir=d, audit_hook=grow)
    report = runner.run()
    assert not report["passed"]
    assert any(f["invariant"] == "memory_ratchet"
               for f in report["findings"])
    detail = [f for f in report["findings"]
              if f["invariant"] == "memory_ratchet"][0]["detail"]
    assert "objects" in detail           # the trend is named
    assert report["counters"]["soak_invariant_failures"] >= 1
    # the failure produced a postmortem bundle next to the report
    bundles = [n for n in os.listdir(d) if n.startswith("postmortem-")]
    assert bundles


@pytest.mark.soak
def test_soak_detects_silently_lost_tuple(tmp_path):
    """A target that drops one record without counting it anywhere must
    fail tuple conservation at the next audit — the 'no silent drops'
    guarantee is only as strong as this test."""

    class LossyTarget(ConnectorSoakTarget):
        lost = False

        def offer_chunk(self, recs):
            if not LossyTarget.lost and len(recs) > 3:
                LossyTarget.lost = True
                recs = recs[:-1]         # one tuple vanishes, uncounted
            super().offer_chunk(recs)

    LossyTarget.lost = False
    runner = SoakRunner(_smoke_config(chaos=ChaosMix()),
                        clock=ManualClock(),
                        report_dir=str(tmp_path / "soak"),
                        make_target=LossyTarget)
    report = runner.run()
    assert not report["passed"]
    f = report["findings"][0]
    assert f["invariant"] == "tuple_conservation"
    assert "+1 tuples unaccounted" in f["detail"]


@pytest.mark.soak
def test_soak_supervised_crash_recovery(tmp_path):
    """One-shot consumer crashes mid-soak: the Supervisor restarts from
    the last checkpoint, the source rewinds to the checkpointed offset,
    and the conservation identity holds through the restart (crashed
    in-flight records are the ABANDONED term; they re-enter via the
    rewind)."""
    d = str(tmp_path / "soak")
    runner = SoakRunner(
        _smoke_config(duration_s=40.0,
                      chaos=ChaosMix(crash_at_chunks=(30, 100)),
                      checkpoint_every_audits=1),
        clock=ManualClock(), report_dir=d)
    report = runner.run()
    assert report["passed"]
    assert report["counters"]["resilience_restarts"] == 2
    assert report["counters"]["resilience_checkpoints"] >= 1
    last = report["audits"][-1]["terms"]
    assert last["seen"] > 60_000         # replayed chunks re-count
    assert last["seen"] == (last["delivered"] + last["shed"]
                            + last["held"] + last["dead_lettered"]
                            + last["abandoned"])


@pytest.mark.soak
def test_soak_recovery_rewind_is_not_a_watermark_violation(tmp_path):
    """A crash AFTER audits have run past the last checkpoint restores a
    rewound watermark — legitimately behind the audited history.
    Monotonicity is a per-generation invariant; the rewind must not
    falsely fail an otherwise healthy soak (code-review regression:
    checkpoint at audit 4, crash near audit 7, first post-recovery
    audit saw wm ~20 s < ~35 s and raised)."""
    d = str(tmp_path / "soak")
    runner = SoakRunner(
        _smoke_config(chaos=ChaosMix(crash_at_chunks=(210,)),
                      checkpoint_every_audits=4),
        clock=ManualClock(), report_dir=d)
    report = runner.run()
    assert report["passed"], report["findings"]
    assert report["counters"]["resilience_restarts"] == 1
    last = report["audits"][-1]["terms"]
    assert last["seen"] == (last["delivered"] + last["shed"]
                            + last["held"] + last["dead_lettered"]
                            + last["abandoned"])


@pytest.mark.soak
def test_soak_gives_up_after_max_restarts(tmp_path):
    from scotty_tpu.resilience.supervisor import SupervisorGaveUp

    runner = SoakRunner(
        _smoke_config(duration_s=40.0,
                      chaos=ChaosMix(crash_at_chunks=(10, 11, 12, 13,
                                                      14, 15)),
                      checkpoint_every_audits=1, max_restarts=2),
        clock=ManualClock(), report_dir=str(tmp_path / "soak"))
    with pytest.raises(SupervisorGaveUp):
        runner.run()
    # the evidence bundle was still written on the failure path
    assert os.path.exists(os.path.join(str(tmp_path / "soak"),
                                       "soak_report.json"))


@pytest.mark.soak
def test_soak_shed_policy_counts_into_identity(tmp_path):
    """policy='shed' with manual pumping: the soak sheds at the ring
    boundary and the identity still balances exactly through the shed
    term (zero silent loss under overload)."""
    runner = SoakRunner(
        _smoke_config(chaos=ChaosMix(), duration_s=20.0,
                      ring=RingConfig(depth=2, block_size=64,
                                      policy="shed", pump_at=0)),
        clock=ManualClock(), report_dir=str(tmp_path / "soak"))
    report = runner.run()
    assert report["passed"]              # shedding is ACCOUNTED loss
    last = report["audits"][-1]["terms"]
    assert last["shed"] > 0
    assert last["seen"] == (last["delivered"] + last["shed"]
                            + last["held"] + last["dead_lettered"]
                            + last["abandoned"])


# -- invariant units --------------------------------------------------------


def test_watermark_monotone_check():
    assert check_watermark_monotone([None, 5, 5, 9]) == []
    bad = check_watermark_monotone([3, 7, 4])
    assert bad and bad[0]["invariant"] == "watermark_monotonicity"
    assert "7 -> 4" in bad[0]["detail"]


def test_ring_bounded_check():
    ok = {"occupancy": 10, "highwater": 16, "depth": 4, "block_size": 4}
    assert check_ring_bounded(ok) == []
    bad = dict(ok, highwater=17)
    out = check_ring_bounded(bad)
    assert out and out[0]["invariant"] == "ring_bounded"


def test_memory_ratchet_check_grace_and_trend():
    flat = [{"rss": 100, "objects": 50}] * 10
    assert check_memory_ratchet(flat, 2, 3, 10, 5) == []
    ramp = [{"rss": 100 + i * 50, "objects": 50} for i in range(10)]
    out = check_memory_ratchet(ramp, 2, 3, 10, 5)
    assert out and out[0]["invariant"] == "memory_ratchet"
    assert "rss" in out[0]["detail"]
    # within grace/slack: no finding
    assert check_memory_ratchet(ramp[:4], 2, 3, 1000, 5) == []


@pytest.mark.soak
@pytest.mark.slow
def test_realtime_soak_two_seconds():
    """A REAL SystemClock soak (excluded from tier-1 by the slow marker;
    the box runs the hours-long versions via the bench Soak cell)."""
    report = SoakRunner(SoakConfig(
        duration_s=2.0, offered_rate=5000.0, chunk_records=256,
        audit_every_s=0.5, seed=1,
        ring=RingConfig(depth=4, block_size=128))).run()
    assert report["passed"]
    assert report["seen"] == 10_240      # ceil over chunk granularity


# -- exactly-once delivery + disk invariants (ISSUE 8) -----------------------


def test_sink_duplicates_check():
    from scotty_tpu.soak import check_sink_duplicates

    assert check_sink_duplicates({(0, 0): 1, (0, 1): 1, (1, 2): 1}) == []
    out = check_sink_duplicates({(0, 0): 1, (0, 1): 3, (1, 2): 2})
    assert out and out[0]["invariant"] == "sink_duplicates"
    assert "(0, 1) x3" in out[0]["detail"]      # worst offender named
    assert "2 (epoch, seq) tag(s)" in out[0]["detail"]


def test_disk_bounded_check(tmp_path):
    from scotty_tpu.soak import check_disk_bounded

    d = str(tmp_path)
    for pos in (4, 8, 12):
        os.makedirs(os.path.join(d, f"ckpt-{pos}"))
    os.makedirs(os.path.join(d, "ckpt-2.tmp"))  # in-flight: never a finding
    assert check_disk_bounded(d, 3) == []
    os.makedirs(os.path.join(d, "ckpt-16"))
    out = check_disk_bounded(d, 3)
    assert out and out[0]["invariant"] == "disk_bounded"
    assert "keep_checkpoints=3" in out[0]["detail"]
    assert "ckpt-16" in out[0]["detail"]        # the evidence named


def test_soak_rejects_unknown_delivery_mode():
    with pytest.raises(ValueError, match="exactly_once"):
        SoakRunner(_smoke_config(delivery="maybe_once"),
                   clock=ManualClock())


@pytest.mark.soak
def test_smoke_soak_exactly_once_with_chaos_crashes(tmp_path):
    """THE ISSUE 8 acceptance soak: exactly-once sink armed, chaos
    consumer crashes mid-run, duplicate + disk invariants on — zero
    invariant failures, real suppression (the crashes DID replay), no
    (epoch, seq) tag delivered twice, checkpoint disk bounded by the
    retention policy, evidence bundle written."""
    d = str(tmp_path / "soak")
    cfg = _smoke_config(
        delivery="exactly_once", keep_checkpoints=3,
        checkpoint_every_audits=2,
        chaos=ChaosMix(late_storm_every=7, poison_pct=0.02,
                       flaky_every=11, crash_at_chunks=(40, 200)))
    runner = SoakRunner(cfg, clock=ManualClock(), report_dir=d)
    report = runner.run()
    assert report["passed"] and report["findings"] == []
    assert runner.supervisor.total_restarts == 2      # both crashes hit
    delivery = report["delivery"]
    assert delivery["mode"] == "exactly_once"
    assert delivery["suppressed"] > 0                 # replays happened
    assert delivery["tags_duplicated"] == 0           # none reached twice
    assert delivery["emitted"] == delivery["tags_observed"]
    # the audits carried the delivery snapshot as evidence
    assert any("delivery" in row for row in report["audits"])
    # disk stayed within retention (the GC actually ran)
    ckpt_dir = os.path.join(d, "checkpoints")
    gens = [n for n in os.listdir(ckpt_dir)
            if n.startswith("ckpt-") and ".tmp" not in n]
    assert 0 < len(gens) <= cfg.keep_checkpoints
    assert os.path.exists(os.path.join(d, "soak_report.json"))
    assert os.path.exists(os.path.join(d, "flight.json"))


def test_soak_sink_duplicate_audit_detects_injected_dupe(tmp_path):
    """The detection path: a harness that can only pass is not evidence.
    A duplicated (epoch, seq) tag injected mid-run must fail the soak at
    the next audit, naming the tag."""
    d = str(tmp_path / "soak")
    runner = SoakRunner(
        _smoke_config(delivery="exactly_once",
                      checkpoint_every_audits=2),
        clock=ManualClock(), report_dir=d)

    def inject(r, row):
        if row["audit"] == 3:
            tag = next(iter(r.sink_tags))
            r.sink_tags[tag] += 1            # the consumer saw it twice

    runner.audit_hook = inject
    report = runner.run()
    assert not report["passed"]
    f = [f for f in report["findings"]
         if f["invariant"] == "sink_duplicates"][0]
    assert "x2" in f["detail"]               # the tag and its count named
    assert report["counters"]["soak_invariant_failures"] >= 1
    # the on-disk evidence bundle carries the same verdict
    on_disk = json.load(open(os.path.join(d, "soak_report.json")))
    assert not on_disk["passed"]


def test_soak_disk_ratchet_detects_gc_failure(tmp_path):
    """Simulated GC failure (extra generations appearing on disk) must
    fail the soak with the offending dirs named. The litter lands right
    after an audit, so the NEXT audit sees it before any commit's GC
    could clean it up — exactly how a real GC regression would present."""
    d = str(tmp_path / "soak")
    runner = SoakRunner(
        _smoke_config(delivery="exactly_once", keep_checkpoints=2,
                      checkpoint_every_audits=4),
        clock=ManualClock(), report_dir=d)

    def litter(r, row):
        if row["audit"] == 5:
            for pos in (9001, 9002, 9003):
                os.makedirs(os.path.join(r.supervisor.dir,
                                         f"ckpt-{pos}"), exist_ok=True)

    runner.audit_hook = litter
    report = runner.run()
    assert not report["passed"]
    f = [f for f in report["findings"]
         if f["invariant"] == "disk_bounded"][0]
    assert "ckpt-9001" in f["detail"]        # the evidence named
    assert "keep_checkpoints=2" in f["detail"]
