"""Session-window operator tests — transliterated from
slicing/src/test/.../windowTest/SessionWindowOperatorTest.java."""

import pytest

from scotty_tpu import (
    SessionWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from conftest import make_operator
from window_assert import assert_contains, assert_window


@pytest.fixture(params=["host", "engine"])
def op(request):
    # engine = the pure-session device path for the in-order single-gap
    # cases; everything else (out-of-order repair, session+tumbling mixes,
    # multi-session) skips to host-only via conftest.SkipUnsupported
    return make_operator(request.param)


def sum_fn():
    return SumAggregation()


def test_in_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 23)
    op.process_element(4, 31)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 1

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 9
    results = op.process_watermark(80)
    assert results[0].get_agg_values()[0] == 5


def test_in_order_clean(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10000))
    op.process_element(1, 1000)
    op.process_element(2, 19000)
    op.process_element(3, 23000)
    op.process_element(4, 31000)
    op.process_element(5, 49000)

    results = op.process_watermark(22000)
    assert results[0].get_agg_values()[0] == 1

    results = op.process_watermark(55000)
    assert results[0].get_agg_values()[0] == 9
    results = op.process_watermark(80000)
    assert results[0].get_agg_values()[0] == 5


def test_in_order_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 0)
    op.process_element(2, 0)
    op.process_element(3, 20)
    op.process_element(4, 31)
    op.process_element(5, 42)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 3

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 5


def test_out_of_order_simple_insert(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)

    op.process_element(1, 9)
    op.process_element(1, 15)
    op.process_element(1, 30)
    op.process_element(1, 12)

    results = op.process_watermark(50)
    assert_window(results[0], 1, 25, 4)
    assert_window(results[1], 30, 40, 1)


def test_out_of_order_right_insert(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)

    op.process_element(1, 9)
    op.process_element(1, 10)
    op.process_element(1, 30)
    op.process_element(1, 12)

    results = op.process_watermark(50)
    assert_window(results[0], 1, 22, 4)
    assert_window(results[1], 30, 40, 1)


def test_out_of_order_left_insert(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)

    op.process_element(1, 9)
    op.process_element(1, 10)
    op.process_element(1, 30)
    op.process_element(1, 27)

    results = op.process_watermark(22)
    assert_window(results[0], 1, 20, 3)

    results = op.process_watermark(50)
    assert_window(results[0], 27, 40, 2)


def test_out_of_order_split_slice(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)

    op.process_element(1, 30)
    op.process_element(1, 12)

    results = op.process_watermark(22)
    assert_window(results[0], 1, 11, 1)

    results = op.process_watermark(50)
    assert_window(results[0], 12, 22, 1)
    assert_window(results[1], 30, 40, 1)


def test_out_of_order_merge_slice(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.process_element(1, 7)

    op.process_element(1, 30)
    op.process_element(1, 51)
    op.process_element(1, 15)
    op.process_element(1, 21)

    results = op.process_watermark(70)
    assert_window(results[0], 7, 40, 4)
    assert_window(results[1], 51, 61, 1)


def test_out_of_order_combined_session_tumbling_merge_session(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 40))
    op.process_element(1, 7)

    op.process_element(1, 22)
    op.process_element(1, 51)
    op.process_element(1, 15)   # merge slice
    op.process_element(1, 37)   # add new session / split

    results = op.process_watermark(70)
    assert_window(results[0], 0, 40, 4)
    assert_window(results[1], 7, 32, 3)
    assert_window(results[2], 37, 47, 1)
    assert_window(results[3], 51, 61, 1)


def test_out_of_order_combined_multi_session(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 10))
    op.add_window_assigner(SessionWindow(WindowMeasure.Time, 5))
    # events -> 20, 31, 33, 40, 50, 57
    # [20-25, 31-38, 40-45, 50-55, 57-62, 20-30, 31-67]
    op.process_element(1, 20)
    op.process_element(1, 40)
    op.process_element(1, 50)
    op.process_element(1, 57)
    op.process_element(1, 33)   # extend one left
    op.process_element(1, 31)   # extend one left

    results = op.process_watermark(70)
    assert_contains(results, 20, 25, 1)
    assert_contains(results, 31, 38, 2)
    assert_contains(results, 40, 45, 1)
    assert_contains(results, 50, 55, 1)
    assert_contains(results, 57, 62, 1)
    assert_contains(results, 20, 30, 1)
    assert_contains(results, 31, 67, 5)


def test_count_measure_session_pinned_oracle_behavior():
    """VERDICT r5 item 6 precondition: pin what count-measure sessions
    ACTUALLY do before building a device path. The reference passes the
    raw event TIMESTAMP to updateContext for every measure
    (SliceManager.java:61/69 — `updateContext(element, ts, ...)`), so a
    count-measure session context runs over ts-space: each tuple farther
    than `gap` (in ts!) from its predecessor opens its own pseudo-session
    [t, t], emitted as [t, t+gap) with measure Count — and the window
    VALUE lookup then runs count containment over those ts-space bounds,
    which is empty unless the ts numbers happen to overlap the count
    range near stream start. Upstream never tests this path; the repo
    keeps it host-only, bit-faithfully (PARITY.md)."""
    from scotty_tpu import (SessionWindow, SlicingWindowOperator,
                            SumAggregation, WindowMeasure)

    op = SlicingWindowOperator()
    op.add_window_assigner(SessionWindow(WindowMeasure.Count, 3))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    for i in range(5):
        op.process_element(float(i + 1), i * 10)
    out = [(w.start, w.end, w.agg_values, w.has_value())
           for w in op.process_watermark(1000)]
    # per-tuple pseudo-sessions in ts-space, [t, t+gap)
    assert [(s, e) for s, e, _, _ in out] == [
        (0, 3), (10, 13), (20, 23), (30, 33), (40, 43)]
    # count containment over ts-space bounds finds nothing here
    assert all(not hv for (_, _, _, hv) in out)

    # ...except when ts numbers overlap the count range near stream
    # start: with gap=2 and a two-tuple burst at ts 0/5, window [0, 2)
    # count-contains the first slice (counts [0, 1)) and reports its sum
    op2 = SlicingWindowOperator()
    op2.add_window_assigner(SessionWindow(WindowMeasure.Count, 2))
    op2.add_aggregation(SumAggregation())
    op2.set_max_lateness(10)
    out2 = []
    for v, t in [(1.0, 0), (2.0, 5), (3.0, 100)]:
        op2.process_element(v, t)
        out2 += [(w.start, w.end, w.agg_values, w.has_value())
                 for w in op2.process_watermark(t + 8)]
    assert (0, 2, [1.0], True) in out2
