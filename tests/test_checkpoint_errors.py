"""Checkpoint error paths the Supervisor depends on (ISSUE 3 satellite):
seed mismatch, treedef/shape mismatch, and snapshot-before-build — each
asserting the SPECIFIC ValueError message survives, since the Supervisor's
recovery loop (and its operators) route users by these strings.

Deliberately light: no pipeline ever runs an interval (reset() allocates
state without tracing a fused step), so this module adds no JAX-tracing
C-stack pressure to the tier-1 sweep (see test_checkpoint_pipelines.py's
isolation note).
"""

import json
import os

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator
from scotty_tpu.engine.pipeline import AlignedStreamPipeline
from scotty_tpu.utils.checkpoint import (
    restore_engine_operator,
    restore_keyed_operator,
    restore_pipeline,
    save_engine_operator,
    save_keyed_operator,
    save_pipeline,
)

Time, Count = WindowMeasure.Time, WindowMeasure.Count
CFG = EngineConfig(capacity=1 << 8, batch_size=64, annex_capacity=32,
                   min_trigger_pad=32)


def make_pipeline(seed=5, capacity=1 << 8):
    import dataclasses

    return AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()],
        config=dataclasses.replace(CFG, capacity=capacity),
        throughput=20_000, wm_period_ms=100, max_lateness=100, seed=seed,
        gc_every=10 ** 9)


def make_op(count=False):
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(TumblingWindow(Count if count else Time,
                                          7 if count else 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(100)
    return op


def test_save_pipeline_before_start_names_the_problem(tmp_path):
    with pytest.raises(ValueError, match="pipeline not started"):
        save_pipeline(make_pipeline(), str(tmp_path / "x"))


def test_restore_pipeline_seed_mismatch_message_survives(tmp_path):
    p = make_pipeline(seed=5)
    p.reset()                               # allocates state; no tracing
    save_pipeline(p, str(tmp_path / "x"))
    with pytest.raises(ValueError, match="seed mismatch: the restored "
                                         "stream would differ"):
        restore_pipeline(make_pipeline(seed=6), str(tmp_path / "x"))


def test_restore_pipeline_wrong_class_message_survives(tmp_path):
    p = make_pipeline()
    p.reset()
    save_pipeline(p, str(tmp_path / "x"))
    meta_path = os.path.join(str(tmp_path / "x"), "meta.json")
    with open(meta_path) as f:
        meta = json.load(f)
    meta["cls"] = "StreamPipeline"
    with open(meta_path, "w") as f:
        json.dump(meta, f)
    with pytest.raises(ValueError,
                       match="StreamPipeline checkpoint, not "
                             "AlignedStreamPipeline"):
        restore_pipeline(make_pipeline(), str(tmp_path / "x"))


def test_restore_pipeline_shape_mismatch_message_survives(tmp_path):
    p = make_pipeline(capacity=1 << 8)
    p.reset()
    save_pipeline(p, str(tmp_path / "x"))
    with pytest.raises(ValueError, match="same configuration as saved"):
        restore_pipeline(make_pipeline(capacity=1 << 9),
                         str(tmp_path / "x"))


def test_save_engine_operator_before_build_names_the_problem(tmp_path):
    with pytest.raises(ValueError, match="not built yet"):
        save_engine_operator(make_op(), str(tmp_path / "op"))


def test_restore_engine_operator_treedef_mismatch_message_survives(tmp_path):
    op_count = make_op(count=True)          # leaves include the record buffer
    op_count.process_elements(np.ones(4, np.float32),
                              np.arange(4, dtype=np.int64))
    save_engine_operator(op_count, str(tmp_path / "op"))
    with pytest.raises(ValueError, match="cannot be migrated"):
        restore_engine_operator(make_op(count=False), str(tmp_path / "op"))


def test_restore_keyed_rejects_non_keyed_snapshot(tmp_path):
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    op = make_op()
    op.process_elements(np.ones(4, np.float32),
                        np.arange(4, dtype=np.int64))
    save_engine_operator(op, str(tmp_path / "op"))
    kop = KeyedTpuWindowOperator(4, config=CFG)
    kop.add_window_assigner(TumblingWindow(Time, 10))
    kop.add_aggregation(SumAggregation())
    with pytest.raises(ValueError, match="not a matching keyed checkpoint"):
        restore_keyed_operator(kop, str(tmp_path / "op"))


def test_save_keyed_before_build_names_the_problem(tmp_path):
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    kop = KeyedTpuWindowOperator(4, config=CFG)
    kop.add_window_assigner(TumblingWindow(Time, 10))
    kop.add_aggregation(SumAggregation())
    with pytest.raises(ValueError, match="not built yet"):
        save_keyed_operator(kop, str(tmp_path / "k"))
