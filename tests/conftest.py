"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding tests run anywhere (SURVEY.md §4e). Must run before any
jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


class SkipUnsupported:
    """Proxy that turns UnsupportedOnDevice into a pytest skip — lets the
    transliterated golden suites run verbatim against the device engine
    (SURVEY.md §4 strategy (a)+(d)): supported workloads are asserted
    identically, host-only workloads (non-associative lambdas, OOO+count,
    session mixes) skip instead of erroring."""

    def __init__(self, inner):
        self._inner = inner

    def __getattr__(self, name):
        from scotty_tpu.engine.operator import UnsupportedOnDevice

        attr = getattr(self._inner, name)
        if not callable(attr):
            return attr

        def call(*a, **k):
            try:
                return attr(*a, **k)
            except UnsupportedOnDevice as e:
                pytest.skip(f"no device path: {e}")

        return call


def make_operator(kind: str):
    """Shared factory for the golden-suite fixtures: ``host`` = the
    reference-semantics simulator, ``engine`` = TpuWindowOperator with a
    tiny shared config (kernel cache keys on the spec — keeping capacities
    identical across tests shares compilations)."""
    if kind == "host":
        from scotty_tpu import SlicingWindowOperator

        return SlicingWindowOperator()
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.operator import TpuWindowOperator

    return SkipUnsupported(TpuWindowOperator(config=EngineConfig(
        capacity=128, annex_capacity=16, batch_size=4)))
