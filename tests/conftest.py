"""Test configuration: force JAX onto a virtual 8-device CPU platform so
multi-chip sharding tests run anywhere (SURVEY.md §4e). Must run before any
jax import."""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
