"""Slice-topology tests for out-of-order repair — transliterated from
slicing/src/test/.../SliceManagerTest.java (shift / split / add / delete
cases driven by a scripted fake context window emitting modifications at
magic timestamps 5/15/25/35)."""

import pytest

from scotty_tpu.core import (
    ForwardContextAware,
    ReduceAggregateFunction,
    WindowContext,
    WindowMeasure,
)
from scotty_tpu.simulator import (
    Flexible,
    LazyAggregateStore,
    LazySlice,
    SliceFactory,
    SliceManager,
    WindowManager,
)
from scotty_tpu.state import MemoryStateFactory


class ScriptedWindowContext(WindowContext):
    """SliceManagerTest.java:297-367 scripted context."""

    def __init__(self, measure):
        super().__init__()
        self.measure = measure

    def update_context(self, tuple_, position):
        index = self.get_window_index(position)
        if index == -1:
            return self.add_new_window(0, position - position % 10,
                                       position + 10 - position % 10)
        elif position % 5 != 0 and position > self.get_window(index).end:
            return self.add_new_window(index + 1, position - position % 10,
                                       position + 10 - position % 10)

        if position == 5:
            self.shift_start(self.get_window(index + 1), position)
        elif position == 15:
            self.shift_start(self.get_window(index), position)
        elif position == 25:
            return self.add_new_window(index, position,
                                       position + 10 - position % 10)
        elif position == 35:
            return self.merge_with_pre(index)
        return None

    def get_window_index(self, position):
        i = 0
        while i < self.number_of_active_windows():
            s = self.get_window(i)
            if s.start <= position and s.end > position:
                return i
            i += 1
        return i - 1

    def assign_next_window_start(self, position):
        return position + 10 - position % 10

    def trigger_windows(self, collector, last_watermark, current_watermark):
        if self.has_no_active_windows():
            return
        w = self.get_window(0)
        while w.end <= current_watermark:
            collector.trigger(w.start, w.end, self.measure)
            self.remove_window(0)
            if self.has_no_active_windows():
                return
            w = self.get_window(0)


class FakeContextWindow(ForwardContextAware):
    def __init__(self, measure):
        self.measure = measure

    def create_context(self):
        return ScriptedWindowContext(self.measure)


@pytest.fixture
def env():
    store = LazyAggregateStore()
    state_factory = MemoryStateFactory()
    window_manager = WindowManager(state_factory, store)
    slice_factory = SliceFactory(window_manager, state_factory)
    slice_manager = SliceManager(slice_factory, store, window_manager)
    window_manager.add_aggregation(ReduceAggregateFunction(lambda a, b: a + b))
    return store, window_manager, slice_factory, slice_manager


def check_records(values, lazy_slice: LazySlice):
    actual = [r.ts for r in lazy_slice.records]
    # the reference helper compares records positionally while records remain
    # (SliceManagerTest.java:289-295) — i.e. actual must be a prefix of values
    assert actual == list(values)[: len(actual)]
    assert len(actual) <= len(values)


def check_slice(s, t_start, t_end, t_first, t_last):
    assert s.t_start == t_start
    assert s.t_end == t_end
    assert s.t_first == t_first
    assert s.t_last == t_last


def test_shift_lower_modification(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible()))
    sm.process_element(1, 1)
    sm.process_element(1, 4)
    sm.process_element(1, 8)
    sm.process_element(1, 9)

    store.append_slice(sf.create_slice_now(10, 20, Flexible()))
    sm.process_element(1, 14)
    sm.process_element(1, 19)

    store.append_slice(sf.create_slice_now(20, 30, Flexible()))
    sm.process_element(1, 24)

    # out-of-order: shift slice start 10->5; move records 8, 9 to next slice
    sm.process_element(1, 5)

    check_slice(store.get_slice(0), 0, 5, 1, 4)
    check_slice(store.get_slice(1), 5, 20, 5, 19)
    check_records([5, 8, 9, 14, 19], store.get_slice(1))


def test_shift_higher_modification(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible()))
    sm.process_element(1, 1)

    store.append_slice(sf.create_slice_now(10, 20, Flexible()))
    sm.process_element(1, 12)
    sm.process_element(1, 14)
    sm.process_element(1, 19)

    store.append_slice(sf.create_slice_now(20, 30, Flexible()))
    sm.process_element(1, 24)

    # out-of-order: shift slice end 10->15; move records 12, 14 back
    sm.process_element(1, 15)

    check_slice(store.get_slice(0), 0, 15, 1, 14)
    check_slice(store.get_slice(1), 15, 20, 15, 19)
    check_records([1, 12, 14, 15], store.get_slice(0))


def test_shift_modification_split(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible(2)))
    assert not store.get_slice(0).type.is_movable()

    sm.process_element(1, 1)
    sm.process_element(1, 4)
    sm.process_element(1, 8)
    sm.process_element(1, 9)

    store.append_slice(sf.create_slice_now(10, 20, Flexible(2)))
    sm.process_element(1, 14)
    sm.process_element(1, 19)

    store.append_slice(sf.create_slice_now(20, 30, Flexible(2)))
    sm.process_element(1, 24)

    # out-of-order: unmovable edge -> split 0-10 into 0-5 / 5-10
    sm.process_element(1, 5)

    check_slice(store.get_slice(0), 0, 5, 1, 4)
    check_slice(store.get_slice(1), 5, 10, 5, 9)
    check_slice(store.get_slice(2), 10, 20, 14, 19)
    check_records([5, 8, 9], store.get_slice(1))


def test_shift_modification_split_2(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible(2)))
    assert not store.get_slice(0).type.is_movable()

    sm.process_element(1, 1)

    store.append_slice(sf.create_slice_now(10, 20, Flexible(2)))
    sm.process_element(1, 12)
    sm.process_element(1, 14)
    sm.process_element(1, 17)
    sm.process_element(1, 19)

    store.append_slice(sf.create_slice_now(20, 30, Flexible(2)))
    sm.process_element(1, 24)

    # out-of-order: split 10-20 into 10-15 / 15-20
    sm.process_element(1, 15)

    check_slice(store.get_slice(0), 0, 10, 1, 1)
    check_slice(store.get_slice(1), 10, 15, 12, 14)
    check_slice(store.get_slice(2), 15, 20, 15, 19)
    check_records([15, 17, 19], store.get_slice(2))


def test_add_modification_split(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible()))
    sm.process_element(1, 1)

    store.append_slice(sf.create_slice_now(10, 20, Flexible()))
    sm.process_element(1, 14)
    sm.process_element(1, 19)

    store.append_slice(sf.create_slice_now(20, 30, Flexible()))
    sm.process_element(1, 22)
    sm.process_element(1, 24)
    sm.process_element(1, 26)
    sm.process_element(1, 27)

    # out-of-order: split 20-30 into 20-25 / 25-30
    sm.process_element(1, 25)

    check_slice(store.get_slice(2), 20, 25, 22, 24)
    check_slice(store.get_slice(3), 25, 30, 25, 27)
    check_records([25, 26, 27, 30], store.get_slice(3))


def test_delete_modification(env):
    store, wm, sf, sm = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    store.append_slice(sf.create_slice_now(0, 10, Flexible()))
    sm.process_element(1, 1)
    store.append_slice(sf.create_slice_now(10, 20, Flexible()))
    sm.process_element(1, 14)
    sm.process_element(1, 19)
    store.append_slice(sf.create_slice_now(20, 30, Flexible()))
    sm.process_element(1, 24)
    store.append_slice(sf.create_slice_now(30, 35, Flexible()))
    sm.process_element(1, 31)
    sm.process_element(1, 33)
    store.append_slice(sf.create_slice_now(35, 45, Flexible()))
    sm.process_element(1, 38)

    sm.process_element(1, 35)  # merge slices 20-30 and 30-35

    check_slice(store.get_slice(2), 20, 35, 24, 33)
    check_slice(store.get_slice(3), 35, 45, 35, 38)
    check_records([24, 31, 33], store.get_slice(2))
