"""Supervised recovery (ISSUE 3): a mid-stream injected crash recovered
by the Supervisor produces final windows bit-identical to an
uninterrupted run — for a fused pipeline (stream = pure function of
(seed, interval)) and for a TpuWindowOperator + replayable source
(source-offset replay). Backoff is deterministic on a ManualClock with
seeded jitter, and recovery events surface as ``resilience_*``
counters/spans.
"""

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator
from scotty_tpu.engine.pipeline import AlignedStreamPipeline
from scotty_tpu.obs import Observability
from scotty_tpu.resilience import (
    ELEMENTS,
    WATERMARK,
    ChaosError,
    CrashInjector,
    ManualClock,
    Supervisor,
    SupervisorGaveUp,
    backoff_delay,
    burst,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


def pipeline_factory(config=None):
    return AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()],
        config=config or CFG, throughput=20_000, wm_period_ms=100,
        max_lateness=100, seed=5, gc_every=10 ** 9, value_scale=1024.0)


def test_pipeline_crash_recovery_bit_matches_uninterrupted(tmp_path):
    obs = Observability()
    clock = ManualClock()
    sup = Supervisor(str(tmp_path / "ckpt"), clock=clock, obs=obs,
                     checkpoint_every=2, max_restarts=2, seed=9)
    crash = CrashInjector(at=5)            # mid-chunk: after 5 intervals,
    rows = sup.run_pipeline(pipeline_factory, 8, fault=crash)
    assert crash.fired == 5                # between checkpoints at 4 and 6

    ref = pipeline_factory()
    ref_rows = [ref.lowered_results(o) for o in ref.run(8)]
    assert rows == ref_rows                # bit-identical tail AND head

    snap = obs.registry.snapshot()
    assert snap["resilience_restarts"] == 1
    assert snap["resilience_checkpoints"] >= 4
    # the backoff slept exactly the seeded schedule on the injected clock
    expect = backoff_delay(1, sup.backoff_base_s, sup.backoff_max_s,
                           sup.jitter, np.random.default_rng(9))
    assert clock.sleeps == [pytest.approx(expect)]
    summary = obs.spans.summary()
    assert "resilience_checkpoint" in summary
    assert "resilience_restore" in summary
    assert "resilience_backoff" in summary


def test_pipeline_supervisor_gives_up_after_bounded_restarts(tmp_path):
    clock = ManualClock()
    sup = Supervisor(str(tmp_path / "ckpt"), clock=clock,
                     checkpoint_every=2, max_restarts=2, seed=1)

    def always_crash(pos):
        raise ChaosError("permanent failure")

    with pytest.raises(SupervisorGaveUp, match="gave up after 2 restarts"):
        sup.run_pipeline(pipeline_factory, 8, fault=always_crash)
    assert len(clock.sleeps) == 2          # backoff per allowed restart
    # bounded exponential: second delay drew from the same seeded rng
    rng = np.random.default_rng(1)
    assert clock.sleeps == [
        pytest.approx(backoff_delay(1, sup.backoff_base_s,
                                    sup.backoff_max_s, sup.jitter, rng)),
        pytest.approx(backoff_delay(2, sup.backoff_base_s,
                                    sup.backoff_max_s, sup.jitter, rng))]


def make_operator(config=None):
    op = TpuWindowOperator(config=config or EngineConfig(
        capacity=1 << 10, batch_size=64, annex_capacity=32,
        min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    return op


def make_events(n_batches=6, per=50):
    vals, ts = burst(seed=3, n=n_batches * per, t0=0, t1=n_batches * 500)
    events = []
    for b in range(n_batches):
        lo = b * per
        events.append((ELEMENTS, vals[lo:lo + per], ts[lo:lo + per]))
        events.append((WATERMARK, int(ts[lo + per - 1])))
    events.append((WATERMARK, n_batches * 500 + 1000))
    return events


def test_operator_source_offset_replay_bit_matches(tmp_path):
    events = make_events()
    obs = Observability()
    sup = Supervisor(str(tmp_path / "ckpt"), clock=ManualClock(), obs=obs,
                     checkpoint_every=3, max_restarts=2, seed=4)
    crash = CrashInjector(at=8)            # between checkpoints at 6 and 9
    got = sup.run_operator(make_operator, events, fault=crash)
    assert crash.fired == 8

    ref_sup = Supervisor(str(tmp_path / "ref"), clock=ManualClock(),
                         checkpoint_every=10 ** 9)
    ref = ref_sup.run_operator(make_operator, events)
    assert got == ref                      # bit-identical emissions
    assert obs.registry.snapshot()["resilience_restarts"] == 1


def test_supervisor_recovers_after_grow(tmp_path):
    """A crash AFTER a GROW doubling must recover: the checkpoint was
    saved from the grown pipeline, so the restart rebuilds at the
    checkpointed (grown) capacity via the config sidecar — rebuilding at
    the factory default would fail the restore leaf-shape check."""
    small = EngineConfig(capacity=64, batch_size=256, annex_capacity=8,
                         min_trigger_pad=32, overflow_policy="grow",
                         max_capacity=1024)

    def factory(config=None):
        return AlignedStreamPipeline(
            [TumblingWindow(Time, 50)], [SumAggregation()],
            config=config or small, throughput=20_000, wm_period_ms=100,
            max_lateness=100, seed=5, gc_every=10 ** 9, value_scale=1024.0)

    N = 40                      # 2 slices/interval vs capacity 64 → grows
    obs = Observability()
    sup = Supervisor(str(tmp_path / "a"), clock=ManualClock(), obs=obs,
                     checkpoint_every=4, max_restarts=2, seed=3)
    crash = CrashInjector(at=34)           # well after growth (~interval 28)
    rows = sup.run_pipeline(factory, N, fault=crash)
    assert crash.fired == 34
    assert obs.registry.snapshot()["resilience_grow_events"] >= 1

    ref_sup = Supervisor(str(tmp_path / "b"), clock=ManualClock(),
                         checkpoint_every=4, max_restarts=0, seed=3)
    assert rows == ref_sup.run_pipeline(factory, N)


def test_restart_budget_resets_on_progress(tmp_path):
    """max_restarts bounds CONSECUTIVE failed recoveries, not the
    lifetime total: two faults far apart, each recovered through a
    checkpoint in between, complete under max_restarts=1."""
    sup = Supervisor(str(tmp_path / "ckpt"), clock=ManualClock(),
                     checkpoint_every=2, max_restarts=1, seed=6)
    fired = []

    def two_faults(pos):
        if pos in (3, 7) and pos not in fired:
            fired.append(pos)
            raise ChaosError(f"transient at {pos}")

    rows = sup.run_pipeline(pipeline_factory, 8, fault=two_faults)
    assert fired == [3, 7]
    assert sup.total_restarts == 2 and sup.restarts <= 1

    ref = pipeline_factory()
    assert rows == [ref.lowered_results(o) for o in ref.run(8)]


def test_checkpoint_commit_is_atomic(tmp_path):
    """A torn checkpoint write (crash between the state files and the
    pointer flip) must be invisible: restarts restore the last COMMITTED
    checkpoint — never new state paired with a stale offset (silent
    double-ingestion) or grown state with a stale config."""
    import os

    events = make_events(n_batches=2)
    d = str(tmp_path / "ckpt")
    sup = Supervisor(d, clock=ManualClock(), checkpoint_every=2)
    sup.run_operator(make_operator, events)
    committed = sup._current_ckpt()
    assert committed is not None
    assert os.path.exists(os.path.join(committed, "offset.json"))

    # torn write: a newer checkpoint directory full of garbage, pointer
    # never flipped
    torn = os.path.join(d, "ckpt-999")
    os.makedirs(torn)
    with open(os.path.join(torn, "meta.json"), "w") as f:
        f.write("{not json")

    sup2 = Supervisor(d, clock=ManualClock(), checkpoint_every=2)
    assert sup2._current_ckpt() == committed     # torn dir ignored
    op, offset = sup2._operator_start(make_operator)
    assert offset == len(events)                 # committed offset, intact


def test_operator_supervisor_without_faults_is_transparent(tmp_path):
    events = make_events(n_batches=3)
    sup = Supervisor(str(tmp_path / "ckpt"), clock=ManualClock(),
                     checkpoint_every=2)
    got = sup.run_operator(make_operator, events)

    op = make_operator()
    plain = []
    for ev in events:
        if ev[0] == ELEMENTS:
            op.process_elements(ev[1], ev[2])
        else:
            ws, we, cnt, low = op.process_watermark_arrays(int(ev[1]))
            plain.append((ws.tolist(), we.tolist(), cnt.tolist(),
                          [np.asarray(lw).tolist() for lw in low]))
    assert got == plain
