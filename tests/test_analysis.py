"""The invariant linter (ISSUE 9 tentpole): every rule fires on its
seeded corpus violation, stays quiet on the paired clean twin, the
framework's suppression/baseline machinery behaves, and — the check
that gates this repo — the REAL tree is clean (this test IS
``analysis check`` running inside tier-1)."""

import json
import pathlib
import subprocess
import sys

import pytest

from scotty_tpu.analysis import (
    Project, RULES, default_root, load_baseline, run_check,
    write_baseline,
)
from scotty_tpu.analysis.core import SUPPRESSION_FORMAT, Finding

CORPUS = pathlib.Path(__file__).parent / "analysis_corpus"

#: rule → (violation file, clean twin, minimum findings in violation)
PAIRS = {
    "no-print": ("no_print_violation.py", "no_print_clean.py", 1),
    "no-sleep": ("no_sleep_violation.py", "no_sleep_clean.py", 2),
    "no-wall-clock": ("no_wall_clock_violation.py",
                      "no_wall_clock_clean.py", 2),
    "fsio-discipline": ("fsio_discipline_violation.py",
                        "fsio_discipline_clean.py", 6),
    "host-sync": ("host_sync_violation.py", "host_sync_clean.py", 3),
    "donation-safety": ("donation_safety_violation.py",
                        "donation_safety_clean.py", 2),
    "flight-kind": ("flight_kind_violation.py",
                    "flight_kind_clean.py", 4),
    "silent-drop": ("silent_drop_violation.py",
                    "silent_drop_clean.py", 2),
    "geometry-discipline": ("geometry_discipline_violation.py",
                            "geometry_discipline_clean.py", 4),
}


def _run_on(rel_files, rule, root=CORPUS):
    project = Project(root, rel_paths=rel_files, doc_paths=())
    new, suppressed, baselined = run_check(
        project, [RULES[rule]], respect_scope=False)
    return new, suppressed


@pytest.mark.parametrize("rule", sorted(PAIRS))
def test_rule_fires_on_violation_corpus(rule):
    vio, _, n_min = PAIRS[rule]
    new, _ = _run_on([vio], rule)
    hits = [f for f in new if f.rule == rule]
    assert len(hits) >= n_min, (
        f"{rule} found {len(hits)} violations in {vio}, "
        f"expected >= {n_min}: {[f.render() for f in new]}")


@pytest.mark.parametrize("rule", sorted(PAIRS))
def test_rule_quiet_on_clean_twin(rule):
    _, clean, _ = PAIRS[rule]
    new, _ = _run_on([clean], rule)
    assert not new, [f.render() for f in new]


@pytest.mark.parametrize("variant,expect_findings", [
    ("coherence_violation", 3),     # 2 typo'd gate keys + 1 doc token
    ("coherence_clean", 0),
])
def test_metric_coherence_on_mini_tree(variant, expect_findings):
    root = CORPUS / variant
    project = Project(
        root, rel_paths=["scotty_tpu/obs/diff.py",
                         "scotty_tpu/obs/registry.py"],
        doc_paths=["docs/API.md"])
    new, _, _ = run_check(project, [RULES["metric-coherence"]],
                          respect_scope=False)
    assert len(new) == expect_findings, [f.render() for f in new]


def test_reasoned_suppression_silences():
    new, suppressed = _run_on(["suppression_reasoned.py"], "no-print")
    assert not new
    assert len(suppressed) == 1 and suppressed[0].rule == "no-print"


def test_reasonless_suppression_is_its_own_finding():
    new, suppressed = _run_on(["suppression_reasonless.py"], "no-print")
    assert not suppressed
    rules = sorted(f.rule for f in new)
    assert rules == sorted(["no-print", SUPPRESSION_FORMAT]), rules


def test_baseline_grandfathers_by_snippet_not_line(tmp_path):
    vio = PAIRS["no-print"][0]
    project = Project(CORPUS, rel_paths=[vio], doc_paths=())
    new, _, _ = run_check(project, [RULES["no-print"]],
                          respect_scope=False)
    assert new
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, new)
    baseline = load_baseline(bl_path)
    again, _, baselined = run_check(project, [RULES["no-print"]],
                                    baseline=baseline,
                                    respect_scope=False)
    assert not again and len(baselined) == len(new)
    # a DIFFERENT finding (other snippet) is not grandfathered
    other = Finding(rule="no-print", path=vio, line=99,
                    message="x", snippet="print('fresh')")
    assert other.key() not in baseline


def test_real_tree_is_clean():
    """`analysis check` inside tier-1: zero new findings on the repo,
    every suppression carrying a reason (reasonless ones surface as
    suppression-format findings and fail here)."""
    root = default_root()
    project = Project(root)
    new, suppressed, _ = run_check(
        project, baseline=load_baseline(
            root / "analysis_baseline.json"))
    assert not new, "\n".join(f.render() for f in new)
    # the suppressions that explain the deliberate sites exist
    assert suppressed, "expected reasoned suppressions in the tree"


def test_every_registered_rule_has_corpus_coverage():
    """A rule without a seeded violation proves nothing — adding a rule
    requires adding its corpus pair (metric-coherence uses the
    mini-trees instead of a flat pair)."""
    covered = set(PAIRS) | {"metric-coherence"}
    assert covered == set(RULES), (
        f"uncovered rules: {set(RULES) ^ covered}")


def test_cli_check_json_and_exit_codes(tmp_path):
    """The CLI face: exit 0 + parseable JSON on the clean tree; exit 1
    when pointed at a tree containing a violation."""
    out = subprocess.run(
        [sys.executable, "-m", "scotty_tpu.analysis", "check",
         "--format", "json"],
        capture_output=True, text=True, cwd=str(default_root()))
    assert out.returncode == 0, out.stdout + out.stderr
    doc = json.loads(out.stdout)
    assert doc["new"] == [] and doc["suppressed"] >= 1
    # a dirty mini-root: one violation file under scotty_tpu/
    dirty = tmp_path / "scotty_tpu"
    dirty.mkdir()
    (dirty / "mod.py").write_text("def f(x):\n    print(x)\n")
    out = subprocess.run(
        [sys.executable, "-m", "scotty_tpu.analysis", "check",
         "--rule", "no-print", "--root", str(tmp_path)],
        capture_output=True, text=True, cwd=str(default_root()))
    assert out.returncode == 1, out.stdout + out.stderr
    assert "no-print" in out.stdout


def test_partial_rule_write_baseline_keeps_other_rules(tmp_path):
    """`check --rule X --write-baseline` must not drop OTHER rules'
    grandfathered entries (review finding: the naive rewrite lost
    them and the next full check went red)."""
    pkg = tmp_path / "scotty_tpu"
    pkg.mkdir()
    # a plain no-sleep finding AND a reasonless no-sleep allow: the
    # partial no-print run can re-derive NEITHER the no-sleep entry nor
    # its suppression-format entry — both must survive via keep
    (pkg / "mod.py").write_text(
        "import time\n\ndef f(x):\n    print(x)\n    time.sleep(1)\n"
        "    time.sleep(2)      # scotty: allow(no-sleep)\n")
    env = [sys.executable, "-m", "scotty_tpu.analysis", "check",
           "--root", str(tmp_path)]
    cwd = str(default_root())
    # grandfather everything, then re-write for ONE rule only
    subprocess.run(env + ["--write-baseline"], capture_output=True,
                   cwd=cwd)
    out = subprocess.run(env + ["--rule", "no-print",
                                "--write-baseline"],
                         capture_output=True, text=True, cwd=cwd)
    assert out.returncode == 0, out.stdout + out.stderr
    bl = load_baseline(tmp_path / "analysis_baseline.json")
    assert any(k[0] == "no-sleep" for k in bl), bl
    assert any(k[0] == SUPPRESSION_FORMAT for k in bl), bl
    out = subprocess.run(env, capture_output=True, text=True, cwd=cwd)
    assert out.returncode == 0, out.stdout + out.stderr


def test_write_baseline_covers_suppression_format(tmp_path):
    """After --write-baseline, the immediate re-check exits 0 even when
    the findings included a reasonless suppression (review finding:
    SUPPRESSION_FORMAT findings skipped the baseline filter)."""
    pkg = tmp_path / "scotty_tpu"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(x):\n    print(x)      # scotty: allow(no-print)\n")
    env = [sys.executable, "-m", "scotty_tpu.analysis", "check",
           "--root", str(tmp_path)]
    cwd = str(default_root())
    out = subprocess.run(env, capture_output=True, text=True, cwd=cwd)
    assert out.returncode == 1
    subprocess.run(env + ["--write-baseline"], capture_output=True,
                   cwd=cwd)
    out = subprocess.run(env, capture_output=True, text=True, cwd=cwd)
    assert out.returncode == 0, out.stdout + out.stderr


def test_pin_hlo_update_refuses_corrupt_pins_file(tmp_path):
    """A corrupt pins file must propagate, not be silently reset — a
    --step subset update over {} would discard the other steps'
    lineage hashes (review finding)."""
    bad = tmp_path / "pins.json"
    bad.write_text('{"schema": "wrong/1", "pins": {}}')
    out = subprocess.run(
        [sys.executable, "-m", "scotty_tpu.analysis", "pin-hlo",
         "--update", "--step", "aligned", "--pins", str(bad)],
        capture_output=True, text=True, cwd=str(default_root()))
    assert out.returncode != 0
    assert "not an hlo-pins file" in (out.stdout + out.stderr)
    # the corrupt file was NOT overwritten
    assert bad.read_text().startswith('{"schema": "wrong/1"')


def test_silent_drop_builtin_set_is_not_evidence(tmp_path):
    """`except Exception: ids = set()` must still flag — the builtin
    constructor is not a counter move (review finding: the bare-name
    arm of the evidence matcher accepted it)."""
    (tmp_path / "mod.py").write_text(
        "def f(sink, rec):\n"
        "    try:\n"
        "        sink(rec)\n"
        "    except Exception:\n"
        "        ids = set()\n"
        "    return ids\n")
    project = Project(tmp_path, rel_paths=["mod.py"], doc_paths=())
    new, _, _ = run_check(project, [RULES["silent-drop"]],
                          respect_scope=False)
    assert len(new) == 1 and new[0].rule == "silent-drop", new


def test_cli_rule_catalog_lists_all_rules():
    out = subprocess.run(
        [sys.executable, "-m", "scotty_tpu.analysis", "check", "--list"],
        capture_output=True, text=True, cwd=str(default_root()))
    assert out.returncode == 0
    for name in RULES:
        assert name in out.stdout
