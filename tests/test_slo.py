"""Per-tenant SLO plane (ISSUE 19): error-budget burn-rate mechanics on
a manual clock, edge-triggered burn/recover/exhaustion events, freshness
objectives naming the stale query slot, the ``/healthz`` SLO check, the
``obs slo`` CLI exit codes, the ``?prefix=`` endpoint filters, and the
``obs diff`` unknown-threshold-key rejection.

Everything here runs on :class:`ManualClock` — the plane's clock
discipline means no test ever sleeps."""

import json
import urllib.error
import urllib.request

import pytest

from scotty_tpu import obs as _obs
from scotty_tpu.obs import HealthPolicy, Observability
from scotty_tpu.obs.attribution import (
    FreshnessTracker,
    TenantAttribution,
    apportion,
)
from scotty_tpu.obs.slo import (
    ENGINE_TENANT,
    OBJECTIVE_DELIVERED_SHARE,
    OBJECTIVE_FRESHNESS,
    ErrorBudget,
    SloPolicy,
    slo_main,
)
from scotty_tpu.resilience.clock import ManualClock


def _get(port, path):
    try:
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                   timeout=5)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# ---------------------------------------------------------------------------
# apportion: exact, deterministic
# ---------------------------------------------------------------------------


def test_apportion_exact_sum_and_deterministic_ties():
    shares = apportion(10, {"a": 1.0, "b": 1.0, "c": 1.0})
    assert sum(shares.values()) == 10
    # largest-remainder ties break by sorted tenant name
    assert shares == apportion(10, {"c": 1.0, "b": 1.0, "a": 1.0})
    # weights respected exactly when they divide evenly
    assert apportion(9, {"x": 2.0, "y": 1.0}) == {"x": 6, "y": 3}
    # no positive weight: everything lands on the min name (never lost)
    all_zero = apportion(5, {"b": 0.0, "a": 0.0})
    assert sum(all_zero.values()) == 5 and all_zero.get("a") == 5
    assert apportion(0, {"a": 1.0}) == {}


# ---------------------------------------------------------------------------
# ErrorBudget: windowed burn, O(1) ledger
# ---------------------------------------------------------------------------


def test_error_budget_burn_and_window_expiry():
    b = ErrorBudget(0.9, fast_window_s=10.0, slow_window_s=100.0)
    assert b.budget == pytest.approx(0.1)
    # 1 bad in 10 ticks = bad_share 0.1 = exactly budget → burn 1.0
    for t in range(9):
        b.record(float(t), good=1, bad=0)
    b.record(9.0, good=0, bad=1)
    assert b.bad_share(9.0, 10.0) == pytest.approx(0.1)
    assert b.burn(9.0, 10.0) == pytest.approx(1.0)
    # the bad tick ages out of the fast window but not the slow one
    b.record(25.0, good=1, bad=0)
    assert b.burn(25.0, 10.0) == pytest.approx(0.0)
    assert b.burn(25.0, 100.0) > 0.0
    # arbitrary (diagnostic) window falls back to a scan, same answer
    assert b.bad_share(25.0, 100.0) == pytest.approx(
        b.bad_share(25.0, 99.5), rel=0.2)
    ev = b.evaluate(25.0)
    assert set(ev) == {"fast_burn", "slow_burn", "exhausted"}


def test_error_budget_validates_inputs():
    with pytest.raises(ValueError):
        ErrorBudget(1.0)
    with pytest.raises(ValueError):
        ErrorBudget(0.0)
    with pytest.raises(ValueError):
        ErrorBudget(0.9, fast_window_s=60.0, slow_window_s=10.0)


# ---------------------------------------------------------------------------
# SloPolicy: edge-triggered latch / recover / exhaustion
# ---------------------------------------------------------------------------


def _burning_policy(clk, obs, ticks=6):
    """Attach attribution + a delivered_share policy and drive ``ticks``
    all-bad ticks for tenant ``hot`` (and all-good for ``calm``)."""
    att = obs.attach_attribution(clock=clk, gauge_every=1)
    pol = obs.attach_slo(delivered_share=0.9, fast_window_s=5.0,
                         slow_window_s=10.0, burn_threshold=2.0,
                         clock=clk)
    for _ in range(ticks):
        att.count("hot", "rejected", 3)
        att.count("calm", "windows", 1)
        clk.advance(1.0)
        obs.flight_sync()
    return att, pol


def test_burn_latch_is_edge_triggered_and_recovers():
    clk = ManualClock()
    obs = Observability(flight=_obs.FlightRecorder(256))
    att, pol = _burning_policy(clk, obs)
    snap = obs.snapshot()
    # one rising edge for (hot, delivered_share) despite 6 burning ticks
    assert snap["slo_burn_events"] == 1
    assert snap["slo_budget_exhausted"] == 1
    assert snap["slo_burning_tenants"] == 1.0
    assert snap["slo_worst_fast_burn"] >= 2.0
    kinds = [e["kind"] for e in obs.flight.events()]
    assert kinds.count("slo_burn") == 1
    assert kinds.count("slo_exhausted") == 1
    v = pol.violations()
    assert len(v) == 1 and v[0]["tenant"] == "hot"
    assert v[0]["objective"] == OBJECTIVE_DELIVERED_SHARE
    assert v[0]["owning_stage"] == "admission"
    # calm tenant never burned
    assert all(row["tenant"] != "calm" for row in v)

    # recovery: good ticks + the bad window aging out → slo_recover
    for _ in range(12):
        att.count("hot", "windows", 5)
        clk.advance(1.0)
        obs.flight_sync()
    assert pol.violations() == []
    kinds = [e["kind"] for e in obs.flight.events()]
    assert kinds.count("slo_recover") == 1
    # burn event count did NOT re-fire during the burning plateau
    assert obs.snapshot()["slo_burn_events"] == 1


def test_one_objective_burn_threshold_needs_both_windows():
    """A fast-only spike must not latch: burning requires fast AND slow
    burn at/over threshold — the SRE multi-window rule."""
    clk = ManualClock()
    obs = Observability()
    att = obs.attach_attribution(clock=clk)
    pol = obs.attach_slo(delivered_share=0.9, fast_window_s=2.0,
                         slow_window_s=50.0, burn_threshold=2.0,
                         clock=clk)
    # long good history fills the slow window
    for _ in range(40):
        att.count("t", "windows", 1)
        clk.advance(1.0)
        pol.evaluate()
    # a 2-tick all-bad spike: fast burn is huge, slow burn still low
    for _ in range(2):
        att.count("t", "rejected", 1)
        clk.advance(1.0)
        res = pol.evaluate()
    assert res["burning"] == []
    assert pol.violations() == []


# ---------------------------------------------------------------------------
# freshness: staleness tracking + the per-query violation row
# ---------------------------------------------------------------------------


def test_freshness_tracker_staleness_and_emission_lag():
    clk = ManualClock(start=100.0)
    fr = FreshnessTracker(clock=clk)
    # slot 3 owned by acme: newest window end 4000 at watermark 5000
    fr.observe({3: [(3000, 4000, 4, ())]}, {3: "acme"}, watermark=5000.0)
    snap = fr.snapshot()
    assert snap[3]["tenant"] == "acme"
    assert snap[3]["emission_lag_ms"] == pytest.approx(1000.0)
    assert snap[3]["staleness_ms"] == pytest.approx(0.0)
    # staleness measures wall progress past the newest window end
    # (event-time 0 pinned to the first observation): 6.5 s of wall
    # elapsed minus the 4000 ms-old newest result = 2500 ms stale
    clk.advance(6.5)
    stale, slot = fr.worst_by_tenant()["acme"]
    assert stale == pytest.approx(2500.0) and slot == 3
    worst_stale, worst_lag = fr.worst()
    assert worst_stale == pytest.approx(2500.0)
    assert worst_lag == pytest.approx(1000.0)
    # slots without a tenant mapping are dropped, not ghosted
    fr.observe({9: [(0, 1000, 1, ())]}, {3: "acme"}, watermark=5000.0)
    assert 9 not in fr.snapshot()


def test_freshness_violation_names_query_slot():
    clk = ManualClock()
    obs = Observability()
    att = obs.attach_attribution(clock=clk)
    pol = obs.attach_slo(freshness_ms=1000.0, freshness_target=0.5,
                         fast_window_s=4.0, slow_window_s=8.0,
                         burn_threshold=1.0, clock=clk)
    att.freshness.observe({7: [(0, 1000, 1, ())]}, {7: "acme"},
                          watermark=1000.0)
    for _ in range(6):                    # stale grows every tick
        clk.advance(1.0)
        pol.evaluate()
    v = pol.violations()
    assert v and v[0]["tenant"] == "acme"
    assert v[0]["objective"] == OBJECTIVE_FRESHNESS
    assert v[0]["query_slot"] == 7


# ---------------------------------------------------------------------------
# /healthz SLO check + ?prefix= filters
# ---------------------------------------------------------------------------


def test_healthz_goes_red_while_burning_and_recovers():
    clk = ManualClock()
    obs = Observability()
    att, pol = _burning_policy(clk, obs)
    with obs.serve(port=0, health=HealthPolicy()) as srv:
        code, text = _get(srv.port, "/healthz")
        assert code == 503
        v = json.loads(text)
        row = v["checks"]["slo"]
        assert not row["ok"]
        assert row["tenant"] == "hot"
        assert row["objective"] == OBJECTIVE_DELIVERED_SHARE
        # recover, then the same endpoint goes green
        for _ in range(12):
            att.count("hot", "windows", 5)
            clk.advance(1.0)
            obs.flight_sync()
        code, _ = _get(srv.port, "/healthz")
        assert code == 200


def test_metrics_and_vars_prefix_filters():
    clk = ManualClock()
    obs = Observability()
    _burning_policy(clk, obs)
    obs.counter("serving_registered").inc(3)
    with obs.serve(port=0) as srv:
        code, text = _get(srv.port, "/metrics?prefix=slo_")
        assert code == 200
        lines = [ln for ln in text.splitlines() if ln]
        assert lines and all("slo_" in ln for ln in lines)
        code, text = _get(srv.port, "/metrics?prefix=serving_")
        assert code == 200 and "serving_registered" in text
        assert "slo_burn_events" not in text
        # an empty filter result is a VALID 200, not an error
        code, text = _get(srv.port, "/metrics?prefix=zz_nothing_")
        assert code == 200
        assert not [ln for ln in text.splitlines()
                    if ln and not ln.startswith("#")]
        code, text = _get(srv.port, "/vars?prefix=slo_")
        assert code == 200
        v = json.loads(text)
        assert all(k.startswith("slo_") for k in v["metrics"])
        assert v["metrics"]                 # the slo gauges survived
        code, text = _get(srv.port, "/vars?prefix=zz_nothing_")
        assert code == 200 and json.loads(text)["metrics"] == {}


# ---------------------------------------------------------------------------
# the CLI verdict: exit 0 / 1 / 2
# ---------------------------------------------------------------------------


def _export_with(pol, obs, path):
    with open(path, "w") as f:
        json.dump(obs.export(), f, default=float)
    return str(path)


def test_slo_cli_green_violation_and_absent(tmp_path):
    clk = ManualClock()
    obs = Observability()
    att, pol = _burning_policy(clk, obs)
    lines = []
    path = _export_with(pol, obs, tmp_path / "burning.json")
    assert slo_main(path, echo=lines.append) == 1
    joined = "\n".join(lines)
    assert "VIOLATION" in joined and "tenant=hot" in joined
    assert "objective=delivered_share" in joined
    assert "owning_stage=admission" in joined

    # json mode carries the violation rows verbatim
    lines = []
    assert slo_main(path, as_json=True, echo=lines.append) == 1
    rows = json.loads("\n".join(lines))["violations"]
    assert rows[0]["tenant"] == "hot"

    # green export → 0
    for _ in range(12):
        att.count("hot", "windows", 5)
        clk.advance(1.0)
        obs.flight_sync()
    lines = []
    green = _export_with(pol, obs, tmp_path / "green.json")
    assert slo_main(green, echo=lines.append) == 0
    assert "green" in lines[0]

    # no SLO section anywhere → 2 (absent plane must not read green)
    bare = tmp_path / "bare.json"
    with open(bare, "w") as f:
        json.dump({"metrics": {"elapsed_s": 1.0}}, f)
    lines = []
    assert slo_main(str(bare), echo=lines.append) == 2
    assert "no SLO section" in lines[0]


# ---------------------------------------------------------------------------
# obs diff: unknown threshold keys rejected with near-misses
# ---------------------------------------------------------------------------


def test_diff_thresholds_reject_unknown_keys(tmp_path):
    from scotty_tpu.obs.diff import (
        DEFAULT_THRESHOLDS,
        known_metric_keys,
        load_thresholds,
    )

    # the slo gates ship in the defaults
    for key in ("slo_budget_exhausted", "slo_burn_events",
                "slo_worst_fast_burn"):
        assert key in DEFAULT_THRESHOLDS["metrics"]

    # a typo'd key is REJECTED, with a did-you-mean hint
    bad = tmp_path / "bad.json"
    with open(bad, "w") as f:
        json.dump({"metrics": {
            "slo_burn_eventz": {"direction": "lower", "default": 0}}}, f)
    with pytest.raises(ValueError) as ei:
        load_thresholds(str(bad))
    msg = str(ei.value)
    assert "slo_burn_eventz" in msg
    assert "slo_burn_events" in msg          # the near-miss hint
    assert "silently" in msg

    # known keys of every shape load fine: a cell row key, a dynamic
    # per-tenant name, and a derived histogram suffix
    ok = tmp_path / "ok.json"
    with open(ok, "w") as f:
        json.dump({"metrics": {
            "tuples_per_sec": {"direction": "higher", "rel_tol": 0.1},
            "slo_tenant_windows_acme": {"direction": "higher"},
            "emit_latency_ms_p99": {"direction": "lower"},
        }}, f)
    loaded = load_thresholds(str(ok))
    assert "tuples_per_sec" in loaded["metrics"]
    known = known_metric_keys()
    assert "slo_burn_events" in known
    assert "tuples_per_sec" in known


def test_policy_without_objectives_never_latches():
    clk = ManualClock()
    obs = Observability()
    obs.attach_attribution(clock=clk)
    pol = obs.attach_slo(clock=clk)       # nothing declared
    for _ in range(5):
        clk.advance(1.0)
        res = pol.evaluate()
    assert res == {"burning": [], "exhausted": [], "worst_fast_burn": 0.0}
    assert pol.violations() == []
    assert pol.export()["tenants"] == {}
    assert ENGINE_TENANT not in pol.export()["tenants"]
