"""Tumbling-window operator tests — transliterated from the reference suite
(slicing/src/test/.../windowTest/TumblingWindowOperatorTest.java). These are
the golden scripted-stream tests: sequences of (value, ts) + watermark points
with hand-computed results."""

import pytest

from scotty_tpu import (
    ReduceAggregateFunction,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)

from conftest import make_operator


@pytest.fixture(params=["host", "engine"])
def op(request):
    return make_operator(request.param)


def sum_fn():
    # SumAggregation: identical host semantics to the reference's
    # ReduceAggregateFunction(a+b) (lift/lower identity, combine +) AND a
    # device realization — so the same goldens drive both operators.
    return SumAggregation()


def test_in_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 1
    assert results[1].get_agg_values()[0] == 2

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 5


def test_in_order_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.process_element(1, 0)
    op.process_element(2, 0)
    op.process_element(3, 20)
    op.process_element(4, 30)
    op.process_element(5, 40)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 3
    assert not results[1].has_value()

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 5


def test_in_order_two_windows(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 1
    assert results[1].get_agg_values()[0] == 2
    assert results[2].get_agg_values()[0] == 3

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 5
    assert results[3].get_agg_values()[0] == 7


def test_in_order_two_windows_dynamic(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))

    op.process_element(1, 1)
    op.process_element(2, 19)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 1
    assert results[1].get_agg_values()[0] == 2
    assert results[2].get_agg_values()[0] == 3

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 5
    assert results[3].get_agg_values()[0] == 7


def test_in_order_two_windows_dynamic_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))

    op.process_element(1, 1)
    op.process_element(2, 19)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 3

    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(55)
    assert results[1].get_agg_values()[0] == 3
    assert results[2].get_agg_values()[0] == 4
    assert results[3].get_agg_values()[0] == 5
    assert results[0].get_agg_values()[0] == 7


def test_out_of_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.process_element(1, 1)

    op.process_element(1, 30)
    op.process_element(1, 20)
    op.process_element(1, 23)
    op.process_element(1, 25)

    op.process_element(1, 45)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 1
    assert not results[1].has_value()

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 1
    assert results[2].get_agg_values()[0] == 1


def test_in_order_count(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 3))
    op.process_element(1, 1)
    op.process_element(1, 19)
    op.process_element(1, 29)
    op.process_element(2, 39)
    op.process_element(2, 49)
    op.process_element(2, 50)
    op.process_element(1, 51)

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3
    assert results[1].get_agg_values()[0] == 6


def test_out_of_order_count(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 3))
    op.process_element(1, 1)
    op.process_element(1, 19)
    op.process_element(1, 29)
    op.process_element(2, 39)
    # out of order
    op.process_element(2, 10)
    op.process_element(2, 50)
    op.process_element(1, 51)

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 4
    assert results[1].get_agg_values()[0] == 5


def test_out_of_order_count_2(op):
    op.add_window_function(sum_fn())
    op.add_window_function(ReduceAggregateFunction(lambda a, b: a - b))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 3))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 5))
    op.process_element(1, 1)
    op.process_element(1, 19)
    op.process_element(1, 29)
    op.process_element(2, 39)
    op.process_element(1, 41)
    # out of order
    op.process_element(2, 10)
    op.process_element(2, 50)
    op.process_element(1, 51)
    op.process_element(3, 52)

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 4
    assert results[1].get_agg_values()[0] == 4
    assert results[2].get_agg_values()[0] == 6
    assert results[3].get_agg_values()[0] == 7


def test_out_of_order_count_3(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 3))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Count, 5))
    op.process_element(1, 1)
    op.process_element(1, 19)
    op.process_element(1, 29)
    op.process_element(2, 39)
    op.process_element(1, 41)
    # out of order
    op.process_element(2, 10)

    results = op.process_watermark(30)
    assert results[0].get_agg_values()[0] == 4

    op.process_element(2, 50)
    op.process_element(1, 51)
    op.process_element(3, 52)
    op.process_watermark(55)
