"""ISSUE 18: the actuation plane. EngineGeometry is one frozen
serializable knob vector (sidecar-committable, cache-keyable);
``apply_geometry`` retunes a LIVE pipeline as a checkpoint-boundary
operation whose twin guarantee — a retuned run bit-matches the
never-retuned oracle — holds across shape-neutral deltas, batch-span
moves in BOTH directions, and capacity growth; the GeometryController
decides retunes with confirm-hysteresis + cooldown and is provably
silent in steady state; the DegradationLadder sheds overload in counted
deterministic rungs with exact conservation, surfaced through /healthz
and the flight recorder."""

import json

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu import obs as _obs
from scotty_tpu.autotune import (
    RUNG_BACKPRESSURE,
    RUNG_NAMES,
    RUNG_NONE,
    ControllerPolicy,
    DegradationLadder,
    EngineGeometry,
    GeometryController,
    GeometryError,
    apply_geometry,
    apply_geometry_operator,
    run_retuned_pipeline,
)
from scotty_tpu.autotune.geometry import SHAPE_AFFECTING
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator
from scotty_tpu.engine.pipeline import AlignedStreamPipeline
from scotty_tpu.ingest import RingConfig
from scotty_tpu.obs.server import HealthPolicy
from scotty_tpu.resilience import ELEMENTS, WATERMARK, ManualClock, Supervisor
from scotty_tpu.serving.cache import GeometryCache
from scotty_tpu.shaper import ShaperConfig

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


def pipeline_factory(config=None):
    return AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()],
        config=config or CFG, throughput=20_000, wm_period_ms=100,
        max_lateness=100, seed=5, gc_every=10 ** 9, value_scale=1024.0)


def _autotune_events(obs):
    return [e["name"] for e in obs.flight.events()
            if e["kind"] == "autotune"]


# -- the geometry value ------------------------------------------------------

def test_geometry_defaults_mirror_module_configs():
    assert EngineGeometry.from_configs(
        engine=EngineConfig(), shaper=ShaperConfig(),
        ring=RingConfig()) == EngineGeometry()


def test_geometry_serde_roundtrip():
    g = EngineGeometry(capacity=1 << 13, batch_size=512,
                       min_trigger_pad=64, micro_batch=4,
                       rows_per_chunk=128, wm_period_ms=100,
                       ring_depth=4, ring_block=256, slack_ms=50,
                       late_capacity=128, pallas_sort_split=True)
    assert EngineGeometry.from_dict(
        json.loads(json.dumps(g.to_dict()))) == g


def test_geometry_sidecar_rejects_unknown_and_non_dict():
    with pytest.raises(GeometryError, match="unknown knobs"):
        EngineGeometry.from_dict({"batch_size": 64, "warp_speed": 9})
    with pytest.raises(GeometryError, match="JSON object"):
        EngineGeometry.from_dict([1, 2, 3])


def test_geometry_validation():
    with pytest.raises(GeometryError):
        EngineGeometry(capacity=0)
    with pytest.raises(GeometryError):
        EngineGeometry(ring_depth=1)
    with pytest.raises(GeometryError):
        EngineGeometry(late_capacity=-1)
    assert issubclass(GeometryError, ValueError)


def test_geometry_derivation_preserves_non_retunable_fields():
    g = EngineGeometry(capacity=1 << 13, batch_size=512, micro_batch=2,
                       slack_ms=40, late_capacity=96, ring_depth=4,
                       ring_block=512)
    e = g.engine_config(EngineConfig(overflow_policy="grow",
                                     annex_capacity=64))
    assert (e.capacity, e.batch_size, e.micro_batch) == (1 << 13, 512, 2)
    assert e.overflow_policy == "grow" and e.annex_capacity == 64
    s = g.shaper_config(ShaperConfig(late_routing="combined"))
    assert (s.slack_ms, s.late_capacity) == (40, 96)
    assert s.late_routing == "combined"
    r = g.ring_config()
    assert (r.depth, r.block_size) == (4, 512)
    # 0 means "module default": block stays batch-derived (None)
    assert g.replace(ring_block=0).ring_config().block_size is None


def test_shape_delta_separates_transplant_from_bit_exact():
    g = EngineGeometry()
    grown = g.replace(batch_size=g.batch_size * 2, micro_batch=4)
    assert grown.shape_delta(g) == frozenset({"batch_size"})
    assert grown.delta(g) == frozenset({"batch_size", "micro_batch"})
    assert "micro_batch" not in SHAPE_AFFECTING
    assert g.shape_delta(g) == frozenset()


# -- live retune twins (the tentpole guarantee) ------------------------------

def _oracle_rows(n):
    ref = pipeline_factory()
    return [ref.lowered_results(o) for o in ref.run(n)]


def _sup(tmp_path, obs=None, name="ck"):
    return Supervisor(str(tmp_path / name), clock=ManualClock(), obs=obs,
                      checkpoint_every=2, max_restarts=2, seed=9)


def test_retune_twin_shape_neutral_delta(tmp_path):
    """A shaper-knob delta (shape_delta empty) restores bit-exactly —
    and still goes through the full drain → commit → rebuild → restore
    path (counted as one retune, one retrace)."""
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    sup = _sup(tmp_path, obs)
    base = EngineGeometry.from_pipeline(pipeline_factory())
    rows = run_retuned_pipeline(
        pipeline_factory, 6, sup,
        schedule={2: base.replace(late_capacity=128)})
    assert rows == _oracle_rows(6)
    snap = obs.registry.snapshot()
    assert snap["autotune_retunes"] == 1
    assert snap["autotune_retraces"] == 1
    names = _autotune_events(obs)
    assert "begin" in names and "retrace" in names and "commit" in names


def test_retune_twin_batch_span_both_directions(tmp_path):
    """The adaptive bench arm's moves: grow the batch span, then shrink
    it back down — the retuned run must bit-match the never-retuned
    oracle through BOTH transplants."""
    base = EngineGeometry.from_pipeline(pipeline_factory())
    sup = _sup(tmp_path)
    rows = run_retuned_pipeline(
        pipeline_factory, 8, sup,
        schedule={2: base.replace(batch_size=8192, late_capacity=32),
                  4: base.replace(batch_size=1024, late_capacity=256)})
    assert rows == _oracle_rows(8)


def test_retune_twin_capacity_growth(tmp_path):
    base = EngineGeometry.from_pipeline(pipeline_factory())
    sup = _sup(tmp_path)
    rows = run_retuned_pipeline(
        pipeline_factory, 6, sup,
        schedule={2: base.replace(capacity=1 << 13)})
    assert rows == _oracle_rows(6)


def test_retune_shrink_capacity_raises_before_committing(tmp_path):
    p = pipeline_factory()
    p.reset()
    p.run(2)
    sup = _sup(tmp_path)
    base = EngineGeometry.from_pipeline(p)
    with pytest.raises(GeometryError, match="shrink"):
        apply_geometry(p, base.replace(capacity=base.capacity // 2),
                       factory=pipeline_factory, supervisor=sup, pos=2)
    assert sup._verified_ckpt() is None      # nothing was committed


def test_retune_equal_geometry_is_identity(tmp_path):
    p = pipeline_factory()
    p.reset()
    p.run(1)
    sup = _sup(tmp_path)
    assert apply_geometry(p, EngineGeometry.from_pipeline(p),
                          factory=pipeline_factory, supervisor=sup,
                          pos=1) is p


def test_retune_warm_cache_skips_recompile(tmp_path):
    """Returning to an already-seen geometry is a warm bucket: the
    GeometryCache hands back the old step, the retrace counter does NOT
    advance, and the twin guarantee still holds."""
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    sup = _sup(tmp_path, obs)
    base = EngineGeometry.from_pipeline(pipeline_factory())
    big = base.replace(batch_size=2048)
    cache = GeometryCache()
    rows = run_retuned_pipeline(
        pipeline_factory, 8, sup, cache=cache,
        schedule={2: big, 4: base})    # out and BACK to the start
    assert rows == _oracle_rows(8)
    snap = obs.registry.snapshot()
    assert snap["autotune_retunes"] == 2
    assert snap["autotune_retraces"] == 1    # the return was warm
    names = _autotune_events(obs)
    assert "warm" in names and names.count("retrace") == 1
    assert cache.hits >= 1


def test_retuned_pipeline_without_schedule_matches_plain_run(tmp_path):
    sup = _sup(tmp_path)
    assert run_retuned_pipeline(pipeline_factory, 4, sup) \
        == _oracle_rows(4)


# -- operator retune ---------------------------------------------------------

def _make_operator(config=None):
    op = TpuWindowOperator(config=config or EngineConfig(
        capacity=1 << 10, batch_size=64, annex_capacity=32,
        min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(500)
    return op


def _int_batches(n_batches=6, per=40):
    rng = np.random.default_rng(7)
    out = []
    for b in range(n_batches):
        vals = rng.integers(0, 100, size=per).astype(np.float64)
        ts = np.sort(rng.integers(b * 200, (b + 1) * 200, size=per))
        out.append((vals, ts))
    return out


def _drive(op, batches, retune_at=None, **retune_kw):
    rows = []
    for i, (vals, ts) in enumerate(batches):
        op.process_elements(vals, ts)
        rows.extend(str(w) for w in op.process_watermark(int(ts[-1])))
        if retune_at is not None and i == retune_at:
            op = apply_geometry_operator(op, pos=i + 1, **retune_kw)
    rows.extend(str(w) for w in op.process_watermark(10_000))
    return rows


def test_operator_retune_twin_launch_knob_delta(tmp_path):
    """A capacity-preserving launch-knob delta (batch span) on the
    batch-at-a-time operator: old state, new geometry, output identical
    to the never-retuned oracle (integer values keep float sums exact
    across the different launch batching)."""
    batches = _int_batches()
    op = _make_operator()
    base = EngineGeometry.from_operator(op)
    target = base.replace(batch_size=128)

    def build(geometry):
        return _make_operator(config=geometry.engine_config(op.config))

    sup = _sup(tmp_path)
    rows = _drive(op, batches, retune_at=2, geometry=target, build=build,
                  supervisor=sup)
    assert rows == _drive(_make_operator(), batches)
    # the committed bundle carries the NEW geometry sidecar
    assert sup.geometry == target


def test_operator_retune_capacity_change_raises(tmp_path):
    op = _make_operator()
    base = EngineGeometry.from_operator(op)
    with pytest.raises(GeometryError, match="capacity"):
        apply_geometry_operator(
            op, base.replace(capacity=base.capacity * 2),
            build=lambda g: _make_operator(), supervisor=_sup(tmp_path),
            pos=0)


# -- the controller ----------------------------------------------------------

G_A = EngineGeometry(batch_size=1024)
G_B = EngineGeometry(batch_size=8192)
G_C = EngineGeometry(batch_size=2048)


def _ctrl(admission, policy=None, candidates=None, current="a"):
    return GeometryController(
        candidates or {"a": G_A, "b": G_B}, admission, current=current,
        policy=policy or ControllerPolicy(confirm=2, cooldown=2,
                                          drift_window=3))


def test_controller_validates_candidates_and_policy():
    with pytest.raises(GeometryError, match="empty"):
        GeometryController({}, lambda g, f: 1.0, current="a")
    with pytest.raises(GeometryError, match="not in candidate set"):
        GeometryController({"a": G_A}, lambda g, f: 1.0, current="z")
    with pytest.raises(GeometryError, match="confirm"):
        ControllerPolicy(confirm=0)


def test_controller_steady_state_is_silent():
    """Zero steady-state retunes, zero flight noise: with the current
    geometry admissible and no drift, every audit returns None and
    writes NOTHING — even when another candidate has more headroom."""
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    ctrl = _ctrl(lambda g, f: float(g.batch_size))   # b always "better"
    for _ in range(50):
        assert ctrl.observe({"arrival_rate_per_s": 10.0},
                            obs=obs) is None
    assert ctrl.decisions == 0 and ctrl.current == "a"
    assert _autotune_events(obs) == []


def test_controller_confirm_hysteresis_and_blip_expiry():
    inadmissible = {"flip": True}

    def admission(g, f):
        if g is G_A:
            return -1.0 if f["flip"] else 5.0
        return 10.0

    ctrl = _ctrl(admission)
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    # audit 1: current inadmissible -> propose b, but do NOT decide yet
    assert ctrl.observe(inadmissible, obs=obs) is None
    # the blip ends: pending expires without a decision
    assert ctrl.observe({"flip": False}, obs=obs) is None
    assert ctrl.decisions == 0
    # a sustained excursion: propose then confirm on the 2nd audit
    assert ctrl.observe(inadmissible, obs=obs) is None
    assert ctrl.observe(inadmissible, obs=obs) == G_B
    assert ctrl.decisions == 1 and ctrl.current == "b"
    names = _autotune_events(obs)
    assert names.count("propose:b") == 2 and names[-1] == "decide:b"


def test_controller_cooldown_sits_out_after_deciding():
    def admission(g, f):
        return -1.0 if g is ctrl.candidates[ctrl.current] else 10.0

    ctrl = _ctrl(lambda g, f: -1.0 if g is G_A else 10.0)
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    ctrl.observe({}, obs=obs)
    assert ctrl.observe({}, obs=obs) == G_B
    # now b is current; make IT inadmissible — cooldown still wins
    ctrl.admission = lambda g, f: -1.0 if g is G_B else 10.0
    for _ in range(2):                       # policy.cooldown audits
        assert ctrl.observe({}, drifted=True, obs=obs) is None
    names = _autotune_events(obs)
    assert names.count("cooldown") == 2
    # cooldown over: the excursion is re-considered from scratch
    ctrl.observe({}, obs=obs)
    assert ctrl.observe({}, obs=obs) == G_A


def test_controller_saturated_cues_the_ladder():
    ctrl = _ctrl(lambda g, f: -5.0)
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    assert ctrl.observe({}, drifted=True, obs=obs) is None
    assert ctrl.saturated is True
    assert "no_admissible" in _autotune_events(obs)
    ctrl.admission = lambda g, f: 5.0
    ctrl.observe({})
    assert ctrl.saturated is False


def test_controller_tiebreak_is_candidate_order():
    """Equal headroom resolves by insertion order, deterministically."""
    cands = {"a": G_A, "b": G_B, "c": G_C}
    ctrl = _ctrl(lambda g, f: -1.0 if g is G_A else 7.0,
                 candidates=cands,
                 policy=ControllerPolicy(confirm=1, cooldown=0))
    assert ctrl.observe({}) == G_B           # b before c, every time


def test_controller_drift_window_considers_moves_while_admissible():
    """A drift event opens the consideration window even when the
    current geometry still admits the load (the excursion may have a
    better home); the window closes after policy.drift_window audits."""
    ctrl = _ctrl(lambda g, f: 1.0 if g is G_A else 10.0)
    assert ctrl.observe({}, drifted=True) is None       # propose b
    assert ctrl.observe({}) == G_B                      # confirm
    assert ctrl.decisions == 1


# -- the degradation ladder --------------------------------------------------

def test_ladder_validation():
    with pytest.raises(GeometryError):
        DegradationLadder(sample_mod=1)
    with pytest.raises(GeometryError):
        DegradationLadder(relax_after=0)
    assert RUNG_NAMES[RUNG_NONE] == "none"
    assert RUNG_NAMES[RUNG_BACKPRESSURE] == "backpressure"


def test_ladder_escalates_relaxes_and_conserves():
    lad = DegradationLadder(sample_mod=4, relax_after=2)
    ts = np.arange(100, dtype=np.int64)
    for expect in (1, 2, 3, 3):              # capped at backpressure
        lad.admit(ts, watermark=50)
        assert lad.conserved
        assert lad.audit(budget=10) == expect
    assert lad.backpressure
    for expect in (3, 2, 2, 1, 1, 0):        # one rung per relax_after
        lad.admit(ts[:5], watermark=0)
        assert lad.audit(budget=1000) == expect
    assert lad.rung == RUNG_NONE and lad.conserved
    assert lad.offered == lad.admitted + lad.shed


def test_ladder_rung1_sheds_exactly_the_late_stratum():
    lad = DegradationLadder()
    lad.admit(np.arange(10), watermark=0)
    lad.audit(budget=1)                      # -> rung 1
    ts = np.array([5, 40, 39, 41, 100])
    keep = lad.admit(ts, watermark=40)
    assert np.array_equal(keep, ts >= 40)


def test_ladder_sampled_admission_is_global_position_deterministic():
    """Rung-2 survivors depend on GLOBAL offered position, so an oracle
    replay of the same offered stream — regardless of how it is split
    into batches — reproduces the survivor set bit-exactly."""

    def escalate(lad):
        for _ in range(2):
            lad.admit(np.arange(8), watermark=100)
            lad.audit(budget=1)
        assert lad.rung == 2

    ts = np.arange(1000, 1097)               # 97 on-time tuples
    a = DegradationLadder(sample_mod=4)
    escalate(a)
    keep_a = a.admit(ts, watermark=1000)
    b = DegradationLadder(sample_mod=4)
    escalate(b)
    parts = [b.admit(ts[:30], watermark=1000),
             b.admit(ts[30:70], watermark=1000),
             b.admit(ts[70:], watermark=1000)]
    assert np.array_equal(keep_a, np.concatenate(parts))
    assert a.shed == b.shed and a.conserved and b.conserved


def test_ladder_flight_edges_and_healthz_rung(tmp_path):
    """Transitions are edge-triggered in the flight ring; the rung gauge
    opts /healthz into the ``degradation`` check, which goes unhealthy
    while any rung is active and recovers fully at rung 0."""
    obs = _obs.Observability(flight=_obs.FlightRecorder())
    lad = DegradationLadder(sample_mod=4, relax_after=1, obs=obs)
    policy = HealthPolicy()
    assert policy.verdict(obs)["checks"]["degradation"]["ok"]
    lad.admit(np.arange(50), watermark=25)
    lad.audit(budget=10)
    v = policy.verdict(obs)
    assert not v["healthy"]
    assert v["checks"]["degradation"] == {"ok": False, "active_rung": 1.0}
    lad.admit(np.arange(3), watermark=10)    # rung 1: all three are late
    lad.audit(budget=1000)                   # relax back to rung 0
    v = policy.verdict(obs)
    assert v["checks"]["degradation"]["ok"] and v["healthy"]
    degrade = [e["name"] for e in obs.flight.events()
               if e["kind"] == "degrade"]
    assert degrade == ["enter:1", "exit:1"]  # edges only, no level spam
    assert obs.counter(_obs.DEGRADE_SHED_TUPLES).value == lad.shed > 0


# -- restart after a committed retune (satellite: supervisor sidecar) --------

def test_restart_after_committed_retune_restores_retuned_geometry(
        tmp_path):
    """The PR 3 config-sidecar discipline, extended to the full knob
    vector: a supervisor that restarts AFTER a committed retune must
    rebuild at the RETUNED geometry (from the geometry.json sidecar),
    not the factory's, and later commits keep carrying it."""
    base = EngineGeometry.from_pipeline(pipeline_factory())
    target = base.replace(batch_size=1024, late_capacity=64)
    sup = _sup(tmp_path)
    run_retuned_pipeline(pipeline_factory, 4, sup, schedule={2: target})

    sup2 = _sup(tmp_path)                    # a fresh process, same dir
    p2 = sup2._pipeline_start(pipeline_factory)
    assert EngineGeometry.from_pipeline(p2) \
        .replace(late_capacity=target.late_capacity) == target
    assert p2.config.batch_size == 1024
    assert sup2.geometry == target
    # the restored pipeline continues bit-identically to the oracle
    assert int(p2._interval) == 4
    rows = [p2.lowered_results(o) for o in p2.run(2)]
    assert rows == _oracle_rows(6)[4:]
