"""The systematic crash-point sweep (ISSUE 8 tentpole part 3): for EVERY
enumerated crash site — each flight-event emit point (ingest batches,
watermarks, drains, emission flushes, epoch commits) plus every
write/fsync/replace *inside* checkpoint commit with torn/short/ENOSPC
variants via the fsio shim — crash a fresh run there, recover under the
Supervisor, and require the delivered sink output be **bit-identical**
to the uninterrupted oracle: zero duplicates, zero losses, site by site.

Full-site sweeps ride tier-1 for the iterable run loop and the aligned
pipeline; the kafka/asyncio loops and the session/count pipelines run a
sampled-site variant (every k-th site) — same oracle discipline, bounded
wall time."""

import os

from scotty_tpu import obs as _obs
from scotty_tpu import (HyperLogLogAggregation, SessionWindow,
                        SlidingWindow, SumAggregation, TumblingWindow,
                        WindowMeasure)
from scotty_tpu.connectors.base import (AscendingWatermarks,
                                        KeyedScottyWindowOperator)
from scotty_tpu.delivery import (EXACTLY_ONCE, TransactionalSink,
                                 asyncio_segment, kafka_segment,
                                 run_supervised)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.resilience import ManualClock, Supervisor
from scotty_tpu.resilience.chaos import (CrashPlan, CrashSite,
                                         crash_point_sweep, make_records)

Time, Count = WindowMeasure.Time, WindowMeasure.Count
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


def _fresh_dir(tmp_path, counter=[0]):
    counter[0] += 1
    d = os.path.join(str(tmp_path), f"env{counter[0]}")
    os.makedirs(d, exist_ok=True)
    return d


def _connector_env_factory(tmp_path, records, run_segment=None,
                           checkpoint_every=16):
    """make_env for the supervised connector loops: fresh obs +
    supervisor + exactly-once sink per run, everything recording through
    ONE Observability so site enumeration is complete."""

    def make_env():
        d = _fresh_dir(tmp_path)
        obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=4096))

        def make_op():
            return KeyedScottyWindowOperator(
                windows=[TumblingWindow(Time, 100)],
                aggregations=[SumAggregation()],
                watermark_policy=AscendingWatermarks(), obs=obs)

        def run():
            sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                             obs=obs, max_restarts=6, seed=11)
            sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
            return run_supervised(records, make_op, sup, sink=sink,
                                  checkpoint_every=checkpoint_every,
                                  run_segment=run_segment,
                                  final_watermark=10_000)

        return obs, run

    return make_env


def _assert_green(report, min_sites=1):
    assert report.sites >= min_sites
    assert report.fired == report.ran       # every armed site was reached
    assert report.oracle_len > 0
    assert report.failures == [], (
        f"{len(report.failures)} of {report.ran} crash sites broke "
        f"exactly-once delivery — first: {report.failures[0]}")


# -- site enumeration sanity -------------------------------------------------

def test_enumeration_covers_flight_and_fs_with_fault_variants(tmp_path):
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(48)]
    make_env = _connector_env_factory(tmp_path, records)
    obs, run = make_env()
    sites = CrashPlan().record(obs, run)
    assert len(sites) >= 40                  # the acceptance floor
    domains = {s.domain for s in sites}
    assert domains == {"flight", "fs"}
    # mid-checkpoint-write sites, with every fault variant
    fs = [s for s in sites if s.domain == "fs"]
    assert {s.fault for s in fs if s.kind == "write"} \
        == {"crash", "torn", "short", "enospc"}
    assert {s.fault for s in fs if s.kind == "fsync"} == {"crash", "eio"}
    assert any(s.kind == "replace" for s in fs)
    names = {s.name for s in fs}
    assert "MANIFEST.json" in names          # the seal itself is a site
    assert "ledger.json" in names            # so is the delivery ledger
    assert any(n.startswith("LATEST.json") for n in names)
    # emission flushes and watermarks are flight sites
    kinds = {s.kind for s in sites if s.domain == "flight"}
    assert "emit" in kinds and "watermark" in kinds
    assert isinstance(sites[0], CrashSite) and sites[0].label()


# -- full-site sweeps (tier-1) -----------------------------------------------

def test_iterable_loop_every_site_exactly_once(tmp_path):
    """The headline sweep: every enumerated site on the supervised
    iterable keyed loop, exactly-once sink armed — recovered output must
    bit-match the uninterrupted oracle at ALL of them."""
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(48)]
    report = crash_point_sweep(_connector_env_factory(tmp_path, records))
    _assert_green(report, min_sites=40)


def test_aligned_pipeline_every_site(tmp_path):
    """Aligned fused pipeline under Supervisor.run_pipeline: the
    'sink output' is the per-interval lowered result rows — positional,
    so recovery must neither lose nor double an interval at any site
    (including mid-checkpoint torn writes and ENOSPC)."""
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def pipeline_factory(config=None):
        return AlignedStreamPipeline(
            [TumblingWindow(Time, 50)], [SumAggregation()],
            config=config or CFG, throughput=20_000, wm_period_ms=100,
            max_lateness=100, seed=5, gc_every=10 ** 9,
            value_scale=1024.0)

    def make_env():
        d = _fresh_dir(tmp_path)
        obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=2048))

        def run():
            sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                             obs=obs, checkpoint_every=2, max_restarts=6,
                             seed=3)
            return sup.run_pipeline(pipeline_factory, 4)

        return obs, run

    report = crash_point_sweep(make_env)
    _assert_green(report, min_sites=40)


# -- sampled-site sweeps -----------------------------------------------------

def test_kafka_loop_sampled_sites(tmp_path):
    records = make_records(seed=13, n=96, keys=3)
    make_env = _connector_env_factory(tmp_path, records,
                                      run_segment=kafka_segment(),
                                      checkpoint_every=32)
    report = crash_point_sweep(make_env, sample_every=7)
    _assert_green(report)


def test_asyncio_loop_sampled_sites(tmp_path):
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(96)]
    make_env = _connector_env_factory(tmp_path, records,
                                      run_segment=asyncio_segment(),
                                      checkpoint_every=32)
    report = crash_point_sweep(make_env, sample_every=7)
    _assert_green(report)


def _pipeline_env_factory(tmp_path, factory, n_intervals=4):
    def make_env():
        d = _fresh_dir(tmp_path)
        obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=2048))

        def run():
            sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                             obs=obs, checkpoint_every=2, max_restarts=6,
                             seed=3)
            return sup.run_pipeline(factory, n_intervals)

        return obs, run

    return make_env


def test_session_pipeline_sampled_sites(tmp_path):
    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    def factory(config=None):
        return SessionStreamPipeline(
            [SessionWindow(Time, 300), SlidingWindow(Time, 500, 100)],
            [HyperLogLogAggregation(6)], config=config or CFG,
            throughput=20_000, wm_period_ms=100, max_lateness=100,
            seed=2,
            session_config={"count": 3, "minGapMs": 300, "maxGapMs": 700})

    report = crash_point_sweep(
        _pipeline_env_factory(tmp_path, factory), sample_every=9)
    _assert_green(report)


def test_count_pipeline_sampled_sites(tmp_path):
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    def factory(config=None):
        del config                           # count pipeline owns its config
        return CountStreamPipeline(
            [TumblingWindow(Count, 7), TumblingWindow(Time, 50)],
            [SumAggregation()], throughput=2000, wm_period_ms=100,
            max_lateness=100, seed=3, out_of_order_pct=0.3)

    report = crash_point_sweep(
        _pipeline_env_factory(tmp_path, factory), sample_every=9)
    _assert_green(report)
