"""Ingest-ring differential + behavior tests (ISSUE 7).

The oracle discipline of the rest of the suite: the vectorized
``offer_block`` path must be EXACTLY equivalent to record-at-a-time
offers, the ring-staged run loops must bit-match their synchronous
(unstaged) twins on every connector, shed survivors must replay to the
same results through a plain loop, and the device-side
``LineRateFeed`` must bit-match ``process_elements``. Chaos values are
small integers (exact in float32) so every comparison is exact.
"""

import asyncio

import numpy as np
import pytest

from scotty_tpu.connectors.base import (
    AscendingWatermarks,
    GlobalScottyWindowOperator,
    KeyedScottyWindowOperator,
)
from scotty_tpu.connectors.iterable import (
    IDLE_TICK,
    collect_global,
    collect_keyed,
    run_keyed,
)
from scotty_tpu.core.aggregates import SumAggregation
from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
from scotty_tpu.ingest import (
    BlockSinkFeeder,
    IngestRing,
    LineRateFeed,
    RingConfig,
    RingFull,
    RingIngestor,
)
from scotty_tpu.obs import Observability
from scotty_tpu.resilience import chaos
from scotty_tpu.resilience.clock import ManualClock
from scotty_tpu.shaper import BatchAccumulator, ShaperConfig

Time = WindowMeasure.Time


def _bounded_ooo(seed, n, step=20, jitter=400):
    rng = chaos.rng_of(seed)
    base = np.arange(n) * step
    ts = np.maximum(base + rng.integers(-jitter, jitter, n), 0)
    vals = rng.integers(0, 100, n)
    return vals.astype(np.float32), ts.astype(np.int64)


# ---------------------------------------------------------------------------
# BatchAccumulator.offer_block ≡ record-at-a-time offers
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("slack,delay", [(0, None), (150, None),
                                         (150, 100.0), (0, 50.0)])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_offer_block_bitmatches_per_record_path(slack, delay, seed):
    vals, ts = _bounded_ooo(seed, 500, step=10, jitter=200)
    blocks_a, blocks_b = [], []
    ca, cb = ManualClock(), ManualClock()
    a = BatchAccumulator(32, lambda v, t: blocks_a.append((v.copy(),
                                                           t.copy())),
                         slack_ms=slack, max_delay_ms=delay, clock=ca)
    b = BatchAccumulator(32, lambda v, t: blocks_b.append((v.copy(),
                                                           t.copy())),
                         slack_ms=slack, max_delay_ms=delay, clock=cb)
    for chunk in np.array_split(np.arange(500), 13):
        for i in chunk:                 # the record-at-a-time path
            a.offer(float(vals[i]), int(ts[i]))
        b.offer_block(vals[chunk], ts[chunk])   # one vectorized block
        ca.advance(0.03)
        cb.advance(0.03)
        a.poll()
        b.poll()
    a.drain()
    b.drain()
    assert len(blocks_a) == len(blocks_b)
    for (va, ta), (vb, tb) in zip(blocks_a, blocks_b):
        assert np.array_equal(va, vb) and np.array_equal(ta, tb)
    assert (a.flushes, a.reordered, a.held_highwater, a.fill_ratios) \
        == (b.flushes, b.reordered, b.held_highwater, b.fill_ratios)


def test_offer_block_expired_deadline_boundary_matches():
    """An already-expired deadline drains after the NEXT record in the
    per-record path; offer_block must hit the same block boundary."""
    blocks_a, blocks_b = [], []
    ca, cb = ManualClock(), ManualClock()
    a = BatchAccumulator(16, lambda v, t: blocks_a.append(t.tolist()),
                         max_delay_ms=50.0, clock=ca)
    b = BatchAccumulator(16, lambda v, t: blocks_b.append(t.tolist()),
                         max_delay_ms=50.0, clock=cb)
    a.offer(1.0, 10)
    b.offer_block([1.0], [10])
    ca.advance(1.0)                     # deadline long expired
    cb.advance(1.0)
    vals = np.arange(5, dtype=np.float32)
    ts = np.arange(5, dtype=np.int64) * 100 + 20
    for v, t in zip(vals, ts):
        a.offer(float(v), int(t))
    b.offer_block(vals, ts)
    a.drain()
    b.drain()
    assert blocks_a == blocks_b
    # the drain fired right after the first new record, not at block end
    assert blocks_a[0] == [10, 20]


def test_offer_block_keyed_object_payloads():
    ts = np.arange(50, dtype=np.int64) * 7
    blocks_a, blocks_b = [], []
    a = BatchAccumulator(8, lambda k, v, t: blocks_a.append(
        (list(k), list(v), t.tolist())), keyed=True, value_dtype=None)
    b = BatchAccumulator(8, lambda k, v, t: blocks_b.append(
        (list(k), list(v), t.tolist())), keyed=True, value_dtype=None)
    keys = [f"k{i % 3}" for i in range(50)]
    payloads = [(i, i * 2) for i in range(50)]   # tuple payloads survive
    for i in range(50):
        a.offer([payloads[i]], [int(ts[i])], keys=[keys[i]])
    b.offer_block(payloads, ts, keys=keys)
    a.drain()
    b.drain()
    assert blocks_a == blocks_b


# ---------------------------------------------------------------------------
# IngestRing mechanics
# ---------------------------------------------------------------------------


def test_ring_fill_commit_take_free_fifo_and_accounting():
    ring = IngestRing(3, 4)
    assert ring.offer_block(np.arange(10, dtype=np.float32),
                            np.arange(10, dtype=np.int64)) == 10
    assert ring.blocks == 2 and ring.occupancy == 10
    blk = ring.take()
    assert blk.seq == 0 and blk.n == 4
    assert blk.ts.tolist()[:4] == [0, 1, 2, 3]
    assert (blk.ts_min, blk.ts_max) == (0, 3)
    ring.free(blk)
    assert ring.delivered == 4 and ring.occupancy == 6
    blk2 = ring.take()
    with pytest.raises(ValueError):     # FIFO free enforced
        b3 = ring.take()
        assert b3 is None or True
        ring.free(type(blk2)(blk2.seq + 5, blk2.vals, blk2.ts, None,
                             blk2.n, 0, 0))
    ring.free(blk2)
    assert ring.flush_open()            # 2 records still open
    blk3 = ring.take()
    assert blk3.n == 2
    ring.free(blk3)
    assert ring.occupancy == 0
    snap = ring.snapshot()
    assert snap["offered"] == snap["delivered"] == 10


def test_ring_full_is_a_signal_not_an_exception():
    ring = IngestRing(2, 4)
    accepted = ring.offer_block(np.zeros(20, np.float32),
                                np.arange(20, dtype=np.int64))
    assert accepted == 8                 # depth*block_size credits
    assert not ring.has_space()
    assert ring.full_events == 1
    assert ring.offer_one(1.0, 99) is False
    assert ring.full_events == 2
    blk = ring.take()
    ring.free(blk)
    assert ring.has_space()


def test_ring_offer_one_scalar_path():
    ring = IngestRing(2, 3, keyed=True, value_dtype=None)
    for i in range(5):
        assert ring.offer_one((i, "payload"), i * 10, key=f"k{i}")
    blk = ring.take()
    assert blk.n == 3 and list(blk.keys[:3]) == ["k0", "k1", "k2"]
    assert list(blk.vals[:3]) == [(0, "payload"), (1, "payload"),
                                  (2, "payload")]
    ring.free(blk)
    assert ring.occupancy == 2


# ---------------------------------------------------------------------------
# RingIngestor policies
# ---------------------------------------------------------------------------


def _sink_collector(collected):
    return lambda vals, tss: collected.append((np.asarray(vals).copy(),
                                               np.asarray(tss).copy()))


def test_policy_block_never_loses_records():
    collected = []
    ring = IngestRing(2, 4, value_dtype=np.float32)
    feeder = BlockSinkFeeder(ring, _sink_collector(collected))
    ing = RingIngestor(ring, feeder, policy="block", pump_at=0)
    vals, ts = np.arange(40, dtype=np.float32), np.arange(40,
                                                          dtype=np.int64)
    assert ing.offer_block(vals, ts) == 40
    ing.drain()
    merged = np.concatenate([t for _, t in collected])
    assert merged.tolist() == ts.tolist()      # everything, in order
    assert ing.shed == 0 and ring.full_events > 0


def test_policy_shed_exact_counts_and_survivor_oracle():
    collected, shed = [], []
    ring = IngestRing(2, 4, value_dtype=np.float32)
    feeder = BlockSinkFeeder(ring, _sink_collector(collected))
    ing = RingIngestor(ring, feeder, policy="shed", pump_at=0,
                       shed_callback=lambda v, t, k: shed.append(
                           (np.asarray(v, np.float32).copy(),
                            np.asarray(t, np.int64).copy())))
    vals, ts = np.arange(40, dtype=np.float32), np.arange(40,
                                                          dtype=np.int64)
    accepted = ing.offer_block(vals, ts)
    assert accepted == 8                 # ring capacity
    assert ing.shed == 32
    ing.drain()
    survivors = np.concatenate([t for _, t in collected])
    shed_ts = np.concatenate([t for _, t in shed])
    # exact conservation: survivors + shed == offered, disjoint, ordered
    assert survivors.tolist() == ts[:8].tolist()
    assert shed_ts.tolist() == ts[8:].tolist()
    snap = ing.snapshot()
    assert snap["offered"] == 8 and snap["shed"] == 32
    assert snap["delivered"] == 8 and snap["occupancy"] == 0


def test_policy_fail_raises_ring_full():
    ring = IngestRing(2, 2, value_dtype=np.float32)
    feeder = BlockSinkFeeder(ring, lambda v, t: None)
    ing = RingIngestor(ring, feeder, policy="fail", pump_at=0)
    with pytest.raises(RingFull):
        ing.offer_block(np.zeros(10, np.float32),
                        np.arange(10, dtype=np.int64))


def test_consumer_stall_trips_watchdog():
    """A slow consumer delivery under blocking backpressure counts a
    resilience_stall_events exactly like a stalled source (PR 3)."""
    clock = ManualClock()
    obs = Observability()
    ring = IngestRing(2, 2, value_dtype=np.float32)

    def slow_sink(vals, tss):
        clock.advance(3.0)               # consumer takes 3 clock-seconds

    feeder = BlockSinkFeeder(ring, slow_sink)
    ing = RingIngestor(ring, feeder, policy="block", pump_at=0, obs=obs,
                       clock=clock, stall_timeout_s=1.0)
    ing.offer_block(np.zeros(10, np.float32),
                    np.arange(10, dtype=np.int64))
    ing.check()                          # drain-point fold
    snap = obs.registry.snapshot()
    assert snap["resilience_stall_events"] >= 1
    assert snap["ingest_ring_full_events"] >= 1


def test_ring_telemetry_folds_exactly_once():
    obs = Observability()
    collected = []
    ring = IngestRing(4, 4, value_dtype=np.float32)
    feeder = BlockSinkFeeder(ring, _sink_collector(collected))
    ing = RingIngestor(ring, feeder, policy="block", pump_at=1, obs=obs)
    ing.offer_block(np.zeros(10, np.float32), np.arange(10,
                                                        dtype=np.int64))
    ing.drain()
    ing.check()                          # double fold must not double count
    snap = obs.registry.snapshot()
    assert snap["ingest_ring_offered"] == 10
    assert snap["ingest_ring_delivered"] == 10
    assert snap["ingest_ring_blocks"] == 3
    assert snap["ingest_ring_occupancy"] == 0


# ---------------------------------------------------------------------------
# ring-staged connector loops ≡ synchronous oracle (every connector)
# ---------------------------------------------------------------------------


def _keyed_recs(seed, n=300):
    vals, ts = _bounded_ooo(seed, n)
    keys = chaos.rng_of(seed + 1).integers(0, 3, n)
    return [(f"k{int(k)}", float(v), int(t))
            for k, v, t in zip(keys, vals, ts)]


def _mk_keyed():
    return KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=1000,
        watermark_policy=AscendingWatermarks())


def _mk_global():
    return GlobalScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=1000,
        watermark_policy=AscendingWatermarks())


_KEY = lambda kw: (kw[0], kw[1].start, kw[1].end,        # noqa: E731
                   tuple(kw[1].agg_values))
_GKEY = lambda w: (w.start, w.end, tuple(w.agg_values))  # noqa: E731


@pytest.mark.parametrize("shaper", [None,
                                    ShaperConfig(batch_size=64,
                                                 slack_ms=1000)])
@pytest.mark.parametrize("seed", [5, 6])
def test_iterable_keyed_ring_bitmatches_unstaged(shaper, seed):
    recs = _keyed_recs(seed)
    out_r = collect_keyed(iter(recs), _mk_keyed(), final_watermark=30_000,
                          ingest_ring=RingConfig(depth=4, block_size=16),
                          shaper=shaper)
    out_p = collect_keyed(iter(recs), _mk_keyed(), final_watermark=30_000,
                          shaper=shaper)
    assert sorted(map(_KEY, out_r)) == sorted(map(_KEY, out_p))


@pytest.mark.parametrize("seed", [7])
def test_iterable_global_ring_bitmatches_unstaged(seed):
    vals, ts = _bounded_ooo(seed, 300)
    recs = [(float(v), int(t)) for v, t in zip(vals, ts)]
    out_r = collect_global(iter(recs), _mk_global(),
                           final_watermark=30_000,
                           ingest_ring=RingConfig(depth=4, block_size=16),
                           shaper=ShaperConfig(batch_size=64,
                                               slack_ms=1000))
    out_p = collect_global(iter(recs), _mk_global(),
                           final_watermark=30_000,
                           shaper=ShaperConfig(batch_size=64,
                                               slack_ms=1000))
    assert sorted(map(_GKEY, out_r)) == sorted(map(_GKEY, out_p))


def test_kafka_ring_bitmatches_unstaged():
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator

    records = chaos.make_records(seed=3, n=150, keys=3, period_ms=40)
    got, ref = [], []
    op_r = _mk_keyed()
    KafkaScottyWindowOperator(operator=op_r).run(
        records, got.append,
        ingest_ring=RingConfig(depth=4, block_size=16))
    got += op_r.process_watermark(30_000)
    op_p = _mk_keyed()
    KafkaScottyWindowOperator(operator=op_p).run(records, ref.append)
    ref += op_p.process_watermark(30_000)
    assert sorted(map(_KEY, got)) == sorted(map(_KEY, ref))


def test_asyncio_ring_bitmatches_unstaged():
    from scotty_tpu.connectors.asyncio_connector import run_keyed_async

    recs = _keyed_recs(9, n=200)

    async def source():
        for r in recs:
            yield r

    def run(ring):
        out = []
        op = _mk_keyed()
        asyncio.run(run_keyed_async(source(), op, out.append,
                                    ingest_ring=ring))
        out += op.process_watermark(30_000)
        return out

    out_r = run(RingConfig(depth=4, block_size=16))
    out_p = run(None)
    assert sorted(map(_KEY, out_r)) == sorted(map(_KEY, out_p))


def test_run_loop_shed_survivors_replay_to_identical_results():
    """policy='shed' with manual pumping: the loop sheds everything past
    the ring's capacity; replaying JUST the survivors through a plain
    loop must produce bit-identical windows (the PR 3 shed-oracle
    discipline at the host edge)."""
    recs = _keyed_recs(11, n=120)
    shed = []
    op_r = _mk_keyed()
    out_r = list(run_keyed(
        iter(recs), op_r,
        ingest_ring=RingConfig(depth=2, block_size=8, policy="shed",
                               pump_at=0),
        shed_callback=lambda v, t, k: shed.extend(
            zip(list(k), list(v), [int(x) for x in t]))))
    out_r += op_r.process_watermark(30_000)
    n_shed = len(shed)
    assert n_shed == 120 - 16            # exactly past-capacity records
    shed_set = {(k, v, t) for k, v, t in shed}
    survivors = [r for r in recs if (r[0], r[1], r[2]) not in shed_set]
    assert len(survivors) == 16
    out_p = collect_keyed(iter(survivors), _mk_keyed(),
                          final_watermark=30_000)
    assert sorted(map(_KEY, out_r)) == sorted(map(_KEY, out_p))


# ---------------------------------------------------------------------------
# idle ticks: a quiet source still flushes on time (ManualClock per loop)
# ---------------------------------------------------------------------------


def _attach_deadline_shaper(op, clock, max_delay_ms=100.0):
    op.attach_shaper(ShaperConfig(batch_size=64,
                                  max_delay_ms=max_delay_ms), clock=clock)
    return op


def test_iterable_idle_tick_flushes_deadline():
    clock = ManualClock()
    op = _attach_deadline_shaper(_mk_keyed(), clock)
    flushed_at_tick = {}

    def source():
        yield ("a", 1.0, 100)
        clock.advance(0.2)               # deadline expires, source quiet
        yield IDLE_TICK
        flushed_at_tick["held"] = op._shaper.held
        flushed_at_tick["flushes"] = op._shaper.accumulator.flushes
        yield ("a", 2.0, 5000)

    list(run_keyed(source(), op))
    # the tick itself flushed the held record — before record 2 arrived
    assert flushed_at_tick == {"held": 0, "flushes": 1}


def test_global_idle_tick_flushes_deadline():
    from scotty_tpu.connectors.iterable import run_global

    clock = ManualClock()
    op = _mk_global()
    op.attach_shaper(ShaperConfig(batch_size=64, max_delay_ms=100.0),
                     clock=clock)
    seen = {}

    def source():
        yield (1.0, 100)
        clock.advance(0.2)
        yield IDLE_TICK
        seen["held"] = op._shaper.held
        yield (2.0, 5000)

    list(run_global(source(), op))
    assert seen == {"held": 0}


def test_kafka_poll_timeout_flushes_deadline():
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
    from scotty_tpu.resilience.chaos import _Record

    clock = ManualClock()
    op = _attach_deadline_shaper(_mk_keyed(), clock)
    state = {"polls": 0, "held_at_empty_poll": None}

    class FakePollConsumer:
        def poll(self, timeout_ms=None):
            state["polls"] += 1
            if state["polls"] == 1:
                return {"tp0": [_Record("a", "1", 100)]}
            clock.advance(0.2)           # quiet topic, clock marches on
            if state["polls"] == 3:
                # by the SECOND empty poll the first one's idle tick
                # must have flushed the held record
                state["held_at_empty_poll"] = op._shaper.held
                return {"tp0": [_Record("a", "2", 5000)]}
            return {}

    KafkaScottyWindowOperator(operator=op).run(
        FakePollConsumer(), lambda item: None, max_records=2,
        idle_poll_ms=50)
    assert state["held_at_empty_poll"] == 0


def test_asyncio_idle_poll_flushes_deadline():
    from scotty_tpu.connectors.asyncio_connector import run_keyed_async

    clock = ManualClock()
    op = _attach_deadline_shaper(_mk_keyed(), clock)
    seen = {}

    async def main():
        gate = asyncio.Event()

        async def source():
            yield ("a", 1.0, 100)
            clock.advance(0.2)           # deadline expired; source silent
            await gate.wait()
            yield ("a", 2.0, 5000)

        async def release():
            # wait until the idle tick flushed, then open the gate
            for _ in range(200):
                await asyncio.sleep(0.005)
                if op._shaper is not None and op._shaper.held == 0 \
                        and op._shaper.accumulator.flushes >= 1:
                    break
            seen["held"] = op._shaper.held
            seen["flushes"] = op._shaper.accumulator.flushes
            gate.set()

        await asyncio.gather(
            run_keyed_async(source(), op, lambda item: None,
                            idle_poll_s=0.01),
            release())

    asyncio.run(main())
    assert seen["held"] == 0 and seen["flushes"] >= 1


def test_ring_idle_tick_flushes_open_partial_block_through_deadline():
    """Records staged in the ring's OPEN partial block must reach the
    operator (and its max_delay_ms machinery) on an idle tick — the
    whole bounded-delay chain, end to end (code-review regression)."""
    clock = ManualClock()
    op = _mk_keyed()
    op.attach_shaper(ShaperConfig(batch_size=64, max_delay_ms=100.0),
                     clock=clock)
    seen = {}

    def source():
        yield ("a", 1.0, 100)
        yield ("a", 2.0, 150)            # both < block_size: open block
        yield IDLE_TICK                  # tick 1: ring → shaper
        seen["ring_after_tick1"] = op._shaper.held
        clock.advance(0.2)               # shaper deadline expires, quiet
        yield IDLE_TICK                  # tick 2: deadline flush
        seen["flushes"] = op._shaper.accumulator.flushes
        seen["held"] = op._shaper.held

    list(run_keyed(source(), op,
                   ingest_ring=RingConfig(depth=4, block_size=16)))
    # tick 1 committed the OPEN ring block into the operator (the
    # records reached the shaper — they no longer wait for stream end);
    # tick 2's poll then fired the shaper's own deadline
    assert seen == {"ring_after_tick1": 2, "flushes": 1, "held": 0}


def test_ring_trickling_source_honors_bounded_delay():
    """A slow-but-ACTIVE source never idles, so without an open-block
    stage deadline its records would sit un-committed in the ring for a
    whole block — the run-loop ring inherits the attached shaper's
    max_delay_ms on the same clock, evaluated on every offer
    (code-review regression)."""
    clock = ManualClock()
    op = _attach_deadline_shaper(_mk_keyed(), clock)
    seen = {}

    def source():
        yield ("a", 1.0, 100)
        clock.advance(0.2)               # > max_delay; source stays busy
        yield ("a", 2.0, 200)            # trips the ring stage deadline:
        seen["in_acc"] = op._shaper.held  # both records now held past it
        clock.advance(0.2)               # accumulator deadline expires
        yield ("a", 3.0, 5000)           # arrival (never an idle tick)
        seen["flushes"] = op._shaper.accumulator.flushes
        seen["held"] = op._shaper.held

    list(run_keyed(source(), op,
                   ingest_ring=RingConfig(depth=4, block_size=16)))
    # record 2's offer committed the open ring block into the
    # accumulator; record 3's arrival evaluated the accumulator
    # deadline (per-arrival parity) and flushed the held records —
    # end-to-end bound <= one ring stage + one accumulator stage
    assert seen["in_acc"] == 2
    assert seen["flushes"] >= 1 and seen["held"] == 0


def test_linerate_feed_rejects_mismatched_block_size():
    """A ring block_size != the operator's batch_size would crash the
    compiled device kernels with an opaque shape error at the first
    dispatched block — refuse it up front (code-review regression)."""
    import scotty_tpu as st
    from scotty_tpu.engine.config import EngineConfig

    op = st.engine.TpuWindowOperator(
        config=EngineConfig(capacity=1 << 10, batch_size=64,
                            annex_capacity=128, min_trigger_pad=32))
    with pytest.raises(ValueError, match="block_size=32 must equal"):
        LineRateFeed(op, ring=RingConfig(depth=4, block_size=32))


def test_ring_drain_paths_count_windows_emitted():
    """Windows yielded from the end-of-stream ring drain (a stream
    shorter than block_size stages EVERYTHING until then) must count
    into the connector-boundary windows_emitted exactly like the
    unstaged loop's — obs-diff parity between ring and non-ring runs
    (code-review regression)."""
    recs = _keyed_recs(11, n=40)         # << default block_size
    obs_p, obs_r = Observability(), Observability()
    out_p = list(run_keyed(iter(recs), _mk_keyed(), obs=obs_p))
    out_r = list(run_keyed(iter(recs), _mk_keyed(), obs=obs_r,
                           ingest_ring=RingConfig(depth=4)))
    assert len(out_p) == len(out_r)
    snap_p = obs_p.registry.snapshot()
    snap_r = obs_r.registry.snapshot()
    assert snap_p.get("windows_emitted", 0) > 0
    assert snap_r.get("windows_emitted", 0) \
        == snap_p.get("windows_emitted", 0)
    assert snap_r.get("ingest_tuples", 0) == snap_p.get("ingest_tuples", 0)


def test_ring_partial_block_delivery_survives_slot_recycling():
    """A partial block delivered mid-stream (idle tick) lands in the
    shaper accumulator's slack band and outlives its ring slot — which
    the producer then overwrites as the ring wraps. The sink must own
    its arrays outright or those held records silently corrupt
    (code-review regression: a depth-2 ring emitted sum 219 where the
    unstaged loop emits 486)."""
    def mk():
        return KeyedScottyWindowOperator(
            windows=[TumblingWindow(Time, 100)],
            aggregations=[SumAggregation()], allowed_lateness=1000,
            watermark_policy=AscendingWatermarks())

    recs = [("a", 100.0, 1), ("a", 200.0, 2), IDLE_TICK] + \
        [("a", float(10 + i), 3 + i) for i in range(12)] + \
        [("a", 1.0, 500)]
    plain = [r for r in recs if r is not IDLE_TICK]
    out_p = list(run_keyed(iter(plain), mk(),
                           shaper=ShaperConfig(batch_size=64)))
    # depth=2 x block_size=4: the idle tick parks 2 records in the
    # accumulator, then the next 8 offers wrap the ring over their slot
    out_r = list(run_keyed(iter(recs), mk(),
                           shaper=ShaperConfig(batch_size=64),
                           ingest_ring=RingConfig(depth=2,
                                                  block_size=4)))
    assert sorted(map(_KEY, out_r)) == sorted(map(_KEY, out_p))


def test_ring_offer_block_preserves_tuple_payloads():
    """Equal-length tuple payloads must arrive downstream verbatim, not
    flattened into ndarray rows (code-review regression — the block and
    scalar paths must agree)."""
    got = []
    ing = RingIngestor.for_sink(
        RingConfig(depth=2, block_size=2),
        lambda keys, vals, tss: got.extend(zip(list(keys), list(vals))),
        keyed=True)
    ing.offer_block([(1, 2), (3, 4), (5, 6)], [100, 200, 300],
                    keys=["a", "b", "c"])
    ing.drain()
    assert got == [("a", (1, 2)), ("b", (3, 4)), ("c", (5, 6))]
    assert all(type(v) is tuple for _, v in got)


def test_kafka_polling_mode_still_flags_stalls():
    """idle_poll_ms must not disable the stall watchdog: a dead producer
    shows as accumulated quiet time across empty polls and flags
    resilience_stall_events (code-review regression)."""
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
    from scotty_tpu.resilience.chaos import _Record

    clock = ManualClock()
    obs = Observability()
    op = _mk_keyed()
    op.obs = obs
    state = {"polls": 0}

    class DeadProducerConsumer:
        def poll(self, timeout_ms=None):
            state["polls"] += 1
            if state["polls"] == 1:
                return {"tp0": [_Record("a", "1", 100)]}
            clock.advance(0.5)           # each empty poll: 0.5 s quiet
            if state["polls"] >= 16:     # producer comes back eventually
                return {"tp0": [_Record("a", "2", 5000)]}
            return {}

    KafkaScottyWindowOperator(operator=op).run(
        DeadProducerConsumer(), lambda item: None, max_records=2,
        idle_poll_ms=50, stall_timeout_s=2.0, clock=clock)
    snap = obs.registry.snapshot()
    # ~7 s of quiet at a 2 s budget → at least two flagged stalls
    assert snap["resilience_stall_events"] >= 2


def test_kafka_polling_mode_confluent_positional_seconds():
    """confluent_kafka's ``Consumer.poll(timeout)`` takes positional
    SECONDS and no ``timeout_ms`` kwarg; polling mode must fall back to
    that face instead of crashing on the very consumers the bare-record
    branch exists for (code-review regression)."""
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
    from scotty_tpu.resilience.chaos import _Record

    clock = ManualClock()
    op = _attach_deadline_shaper(_mk_keyed(), clock)
    state = {"polls": 0, "timeouts": [], "held_at_empty_poll": None}

    class FakeConfluentConsumer:
        def poll(self, timeout):         # positional seconds, no kwargs
            state["polls"] += 1
            state["timeouts"].append(timeout)
            if state["polls"] == 1:
                return _Record("a", "1", 100)     # one bare record
            clock.advance(0.2)
            if state["polls"] == 3:
                state["held_at_empty_poll"] = op._shaper.held
                return _Record("a", "2", 5000)
            return None

    n = KafkaScottyWindowOperator(operator=op).run(
        FakeConfluentConsumer(), lambda item: None, max_records=2,
        idle_poll_ms=50)
    assert n == 2
    # the fallback converted ms → seconds for the positional face
    assert state["timeouts"][-1] == pytest.approx(0.05)
    # and the empty-poll idle tick still flushed the held record
    assert state["held_at_empty_poll"] == 0


def test_bounded_queue_default_and_unbounded_flight_mark():
    from scotty_tpu.connectors.asyncio_connector import (
        DEFAULT_QUEUE_MAXSIZE,
        bounded_queue,
        queue_source,
    )
    from scotty_tpu.obs import FlightRecorder

    async def main():
        q = bounded_queue()
        assert q.maxsize == DEFAULT_QUEUE_MAXSIZE
        with pytest.raises(ValueError):
            bounded_queue(0)
        # producer-side contract: put_nowait raises at the bound
        small = bounded_queue(1)
        small.put_nowait(1)
        with pytest.raises(asyncio.QueueFull):
            small.put_nowait(2)
        # an unbounded queue is flight-marked, a bounded one is not
        obs = Observability(flight=FlightRecorder(capacity=64))
        unbounded = asyncio.Queue()
        await unbounded.put(None)        # sentinel terminates immediately
        async for _ in queue_source(unbounded, obs=obs):
            pass
        marks = [e for e in obs.flight.events()
                 if e["name"] == "queue_source_unbounded"]
        assert len(marks) == 1

    asyncio.run(main())


# ---------------------------------------------------------------------------
# LineRateFeed (device path): prefetch ring ≡ process_elements oracle
# ---------------------------------------------------------------------------


from scotty_tpu.engine import EngineConfig  # noqa: E402
from scotty_tpu.engine.operator import TpuWindowOperator  # noqa: E402

SMALL = EngineConfig(capacity=1 << 12, batch_size=64, annex_capacity=256,
                     min_trigger_pad=32)


def _mk_device_op():
    op = TpuWindowOperator(config=SMALL)
    op.add_window_assigner(TumblingWindow(Time, 1000))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(2000)
    return op


def _windows_dict(ws, we, cnt, lowered):
    return {(int(s), int(e)): (int(c), tuple(float(x) for x in row))
            for s, e, c, *row in zip(ws, we, cnt, *lowered) if c > 0}


@pytest.mark.parametrize("shaped", [True, False])
@pytest.mark.parametrize("seed", [0, 1])
def test_linerate_feed_bitmatches_process_elements(shaped, seed):
    if shaped:
        vals, ts = _bounded_ooo(seed, 1000, step=20, jitter=400)
        shaper = ShaperConfig(slack_ms=500)
    else:
        # in-order mode: strict ascending stream (the sorted fast path)
        ts = (np.arange(1000) * 20).astype(np.int64)
        vals = chaos.rng_of(seed).integers(0, 100, 1000) \
            .astype(np.float32)
        shaper = None
    op1 = _mk_device_op()
    feed = LineRateFeed(op1, ring=RingConfig(depth=4), shaper=shaper)
    for i in range(0, 1000, 100):
        feed.offer_block(vals[i:i + 100], ts[i:i + 100])
    # mid-stream watermark exercises the drain-at-watermark wiring
    mid = _windows_dict(*op1.process_watermark_arrays(int(ts[500])))
    out1 = _windows_dict(*op1.process_watermark_arrays(30_000))
    op1.check_overflow()

    op2 = _mk_device_op()
    op2.process_elements(vals[:500], ts[:500])
    # the oracle sees the same records split at the same watermark: the
    # feed drains everything held at its watermark, so records 0..499
    # land before it and 500.. after
    mid2_idx = 500
    mid2 = _windows_dict(*op2.process_watermark_arrays(int(ts[500])))
    op2.process_elements(vals[mid2_idx:], ts[mid2_idx:])
    out2 = _windows_dict(*op2.process_watermark_arrays(30_000))
    op2.check_overflow()
    assert mid == mid2
    assert out1 == out2
    snap = feed.snapshot()
    assert snap["offered"] == 1000 and snap["occupancy"] == 0
    assert snap["shed"] == 0


def test_obs_diff_gates_ring_and_soak_counters(tmp_path):
    import json

    from scotty_tpu.obs.diff import DEFAULT_THRESHOLDS, diff_exports

    for name in ("ingest_ring_shed", "ingest_ring_full_events",
                 "soak_invariant_failures"):
        assert name in DEFAULT_THRESHOLDS["metrics"]
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    row = {"name": "cell", "windows": "w", "engine": "e",
           "aggregation": "sum", "tuples_per_sec": 100.0}
    base.write_text(json.dumps([row]))
    cand.write_text(json.dumps([dict(row, ingest_ring_shed=5,
                                     soak_invariant_failures=1)]))
    bad = {f["metric"] for f in diff_exports(str(base), str(cand))
           if f["status"] == "regressed"}
    assert {"ingest_ring_shed", "soak_invariant_failures"} <= bad


def test_ingest_external_runner_cell_smoke():
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_ingest_external_cell

    cfg = BenchmarkConfig(
        name="t", throughput=60_000, runtime_s=2, batch_size=4096,
        capacity=1 << 14, watermark_period_ms=500, max_lateness=500,
        seed=3)
    res = run_ingest_external_cell(cfg, "Sliding(2000,500)", "sum")
    assert res.tuples_per_sec > 0
    assert res.speedup_vs_per_record > 0
    assert 0.0 <= res.prefetch_overlap_ratio <= 1.0
    assert res.ring_shed == 0
    assert res.ring_occupancy_p99 >= res.ring_occupancy_p50 >= 0


def test_soak_runner_cell_smoke():
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_soak_cell

    cfg = BenchmarkConfig(name="t", soak_seconds=1.0,
                          offered_rate=4000.0, seed=3)
    res = run_soak_cell(cfg, "Sliding(2000,500)", "sum")
    assert res.soak_passed and res.soak_findings == []
    assert res.soak_seen >= 4000
    t = res.soak_last_terms
    assert t["seen"] == (t["delivered"] + t["shed"] + t["held"]
                         + t["dead_lettered"] + t["abandoned"])


def test_linerate_feed_deadline_poll_flushes():
    clock = ManualClock()
    op = _mk_device_op()
    feed = LineRateFeed(op, ring=RingConfig(depth=4),
                        shaper=ShaperConfig(max_delay_ms=100.0),
                        clock=clock)
    feed.offer_block(np.arange(5, dtype=np.float32),
                     np.arange(5, dtype=np.int64) * 10)
    assert feed.held == 5
    clock.advance(0.2)
    feed.poll()                          # idle tick: deadline flush
    assert feed.accumulator.held == 0
    assert feed.held == 0                # delivered through to the device
    # first-watermark convention enumerates triggers from wm -
    # max_lateness, so stay within reach of the [0, 1000) window
    out = _windows_dict(*op.process_watermark_arrays(1_500))
    assert out                           # the records actually landed
    op.check_overflow()
