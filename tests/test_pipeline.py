"""Differential tests: the fused AlignedStreamPipeline vs the host oracle.

The aligned pipeline is the benchmark execution mode (bench.py): the paced
generator emits tuples grouped by slice row and ingest is a dense row
reduction. These tests materialize the pipeline's own generated stream
(``materialize_interval`` replays the device RNG bit-exactly), feed it to the
reference-semantics simulator, and require identical window results at every
watermark — the same oracle strategy as test_engine_differential.py.
"""

import numpy as np
import pytest

from scotty_tpu import (
    MaxAggregation,
    MeanAggregation,
    MinAggregation,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.pipeline import AlignedStreamPipeline

Time = WindowMeasure.Time

CFG = EngineConfig(capacity=1 << 12, annex_capacity=8, min_trigger_pad=32)


def run_diff(windows, agg_factories, throughput, wm_period, n_intervals,
             seed=0, oracle="sim"):
    """oracle='sim': reference-semantics simulator (exact parity — valid when
    every window size is a multiple of its slide, so reference slices never
    straddle a window end). oracle='exact': brute-force per-window recompute
    from the raw tuples — used for size%slide!=0 specs, where the reference
    SILENTLY DROPS the straddling slice's in-window tuples
    (AggregateWindowState.java:25-31 t_last containment over the coarse slide
    grid); the aligned pipeline deliberately deviates by slicing on
    gcd(sizes, slides) so every window aggregate is exact."""
    p = AlignedStreamPipeline(
        windows, [mk() for mk in agg_factories], config=CFG,
        throughput=throughput, wm_period_ms=wm_period, seed=seed,
        gc_every=10 ** 9)
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    for mk in agg_factories:
        sim.add_aggregation(mk())
    sim.set_max_lateness(1000)
    aggs = [mk() for mk in agg_factories]
    all_vals = []
    all_ts = []

    p.reset()
    for i in range(n_intervals):
        out = p.run(1)[0]
        vals, ts = p.materialize_interval(i)
        wm = (i + 1) * wm_period
        if oracle == "sim":
            order = np.argsort(ts, kind="stable")
            for v, t in zip(vals[order], ts[order]):
                sim.process_element(float(v), int(t))
            r_sim = [w for w in sim.process_watermark(wm) if w.has_value()]
            oracle_map = {}
            for w in r_sim:
                oracle_map.setdefault((w.get_start(), w.get_end()),
                                      w.get_agg_values())
        else:
            all_vals.append(vals)
            all_ts.append(ts)
            cat_v = np.concatenate(all_vals)
            cat_t = np.concatenate(all_ts)
            oracle_map = {}
            for w in windows:
                s_arr, e_arr = w.trigger_arrays(i * wm_period, wm)
                for s, e in zip(s_arr, e_arr):
                    m = (cat_t >= s) & (cat_t < e)
                    if m.any():
                        sel = cat_v[m].astype(np.float64)
                        row = []
                        for a in aggs:
                            part = a.lift(float(sel[0]))
                            for v in sel[1:]:
                                part = a.combine(part, a.lift(float(v)))
                            row.append(a.lower(part))
                        oracle_map.setdefault((int(s), int(e)), row)
        rows = p.lowered_results(out)

        pipe_map = {(s, e): v for (s, e, c, v) in rows}
        assert set(pipe_map) == set(oracle_map), (
            f"interval {i} @wm={wm}: window-set mismatch "
            f"{set(oracle_map) ^ set(pipe_map)}")
        for k2 in oracle_map:
            for a, b in zip(oracle_map[k2], pipe_map[k2]):
                assert float(a) == pytest.approx(float(b), rel=2e-4), (
                    i, k2, oracle_map[k2], pipe_map[k2])
    p.check_overflow()
    return p


def test_aligned_sliding_tumbling_mix():
    run_diff([SlidingWindow(Time, 60, 20), TumblingWindow(Time, 50)],
             [SumAggregation, MaxAggregation],
             throughput=3000, wm_period=100, n_intervals=6)


def test_aligned_size_not_multiple_of_slide():
    # Sliding(25,10): window ends are ≡ 5 (mod 10) — the straddling-slice
    # containment hole of coarse grids; the aligned grid = gcd(25,10) = 5
    # puts every end on a slice edge.
    p = run_diff([SlidingWindow(Time, 25, 10)],
                 [SumAggregation, MinAggregation],
                 throughput=4000, wm_period=100, n_intervals=5,
                 oracle="exact")
    assert p.grid == 5


def test_aligned_1ms_grid_boundary_windows():
    # slide 1: every watermark has a boundary window with end == wm + 1
    # (the reference's <= wm+1 sliding guard, incl. its re-emission quirk);
    # differential equality proves the trigger grid reproduces it.
    run_diff([SlidingWindow(Time, 60, 1)], [SumAggregation],
             throughput=2000, wm_period=20, n_intervals=8)


def test_aligned_mean_width2():
    run_diff([TumblingWindow(Time, 40)], [MeanAggregation, SumAggregation],
             throughput=2500, wm_period=80, n_intervals=5)


def test_aligned_gc_preserves_results():
    # gc_every=2 forces GC mid-run; results must stay identical
    windows = [SlidingWindow(Time, 60, 20)]
    p = AlignedStreamPipeline(windows, [SumAggregation()], config=CFG,
                              throughput=3000, wm_period_ms=100, gc_every=2,
                              max_lateness=100)
    q = AlignedStreamPipeline(windows, [SumAggregation()], config=CFG,
                              throughput=3000, wm_period_ms=100,
                              gc_every=10 ** 9, max_lateness=100)
    p.reset()
    q.reset()
    for i in range(8):
        rp = p.lowered_results(p.run(1)[0])
        rq = q.lowered_results(q.run(1)[0])
        assert [(s, e, c) for s, e, c, _ in rp] == \
               [(s, e, c) for s, e, c, _ in rq], (i, rp, rq)
        for (_, _, _, va), (_, _, _, vb) in zip(rp, rq):
            for a, b in zip(va, vb):
                # prefix sums re-associate after the GC roll → f32 rounding
                assert float(a) == pytest.approx(float(b), rel=1e-5)
    p.check_overflow()


def test_aligned_out_of_order_matches_simulator():
    """The aligned pipeline's OOO mode (late lanes folded into covering
    slices at the START of each interval, before the base append) must
    emit the same windows as the simulator fed the identical regenerated
    stream in the same arrival order: interval i's late tuples (event
    times in [base - lateness, base)) first, then its base stream."""
    LAT, P = 50, 100
    windows = [SlidingWindow(Time, 60, 20), TumblingWindow(Time, 40)]
    p = AlignedStreamPipeline(
        windows, [SumAggregation(), MaxAggregation()], config=CFG,
        throughput=3000, wm_period_ms=P, max_lateness=LAT, seed=11,
        gc_every=4, out_of_order_pct=0.1)
    assert p.n_late > 0
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.add_aggregation(MaxAggregation())
    sim.set_max_lateness(LAT)

    p.reset()
    for i in range(8):
        out = p.run(1)[0]
        lvals, lts = p.materialize_interval_late(i)
        for v, t in zip(lvals, lts):
            sim.process_element(float(v), int(t))
        vals, ts = p.materialize_interval(i)
        order = np.argsort(ts, kind="stable")
        for v, t in zip(vals[order], ts[order]):
            sim.process_element(float(v), int(t))
        wm = (i + 1) * P
        want = {}
        for w in sim.process_watermark(wm):
            if w.has_value():
                want.setdefault((w.get_start(), w.get_end()),
                                w.get_agg_values())
        got = {(s, e): v for (s, e, c, v) in p.lowered_results(out)}
        assert set(got) == set(want), (i, set(want) ^ set(got))
        for k in want:
            for a, b in zip(want[k], got[k]):
                assert float(a) == pytest.approx(float(b), rel=2e-4), (i, k)
    p.check_overflow()


def test_stream_pipeline_out_of_order_matches_simulator():
    """The fused OOO pipeline (in-order base + sorted late sub-batch per
    scan step, annex merged per interval) must emit the same windows as the
    simulator fed the identical regenerated stream in the same order."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scotty_tpu import (SlicingWindowOperator, SumAggregation,
                            TumblingWindow, WindowMeasure)
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.pipeline import StreamPipeline

    Time = WindowMeasure.Time
    P, LAT = 100, 50
    p = StreamPipeline(
        [TumblingWindow(Time, 20)], [SumAggregation()],
        config=EngineConfig(capacity=1 << 10, annex_capacity=256,
                            min_trigger_pad=32),
        throughput=2000 * 1000 // P, wm_period_ms=P, max_lateness=LAT,
        seed=3, sub_batch=256, out_of_order_pct=0.1)
    assert p.B_late > 0
    p.reset()
    outs = p.run(5, collect=True)

    # regenerate the exact device stream on host (same fold_in tree)
    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 20))
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(LAT)
    root = jax.random.PRNGKey(3)
    B, BL, G = p.B, p.B_late, p.G
    n_late = int(B * p.out_of_order_pct)
    span = P / G
    for i in range(5):
        key = jax.random.fold_in(root, i)
        for g in range(G):
            kg = jax.random.fold_in(key, jnp.int64(g))
            lo = np.float64(i * P + g * span)
            gaps = np.asarray(jax.random.uniform(kg, (B,), dtype=jnp.float32))
            gaps = gaps / gaps.sum() * span
            ts = (np.int64(lo) + np.cumsum(gaps).astype(np.int64))
            vals = np.asarray(jax.random.uniform(kg, (B,),
                                                 dtype=jnp.float32)) * 10_000
            sim.process_elements(vals, ts)
            kl = jax.random.fold_in(kg, 7)
            u = np.asarray(jax.random.uniform(kl, (2, BL),
                                              dtype=jnp.float32))
            lo_l = max(lo - LAT, 0.0)
            lts = (lo_l + np.sort(u[0]).astype(np.float64)
                   * (lo - lo_l)).astype(np.int64)
            sim.process_elements(u[1][:n_late] * 10_000.0, lts[:n_late])
        want = sim.process_watermark((i + 1) * P)
        got = p.lowered_results(outs[i])
        want_rows = [(w.get_start(), w.get_end(),
                      float(w.get_agg_values()[0]))
                     for w in want if w.has_value()]
        got_rows = [(s, e, float(v[0])) for s, e, c, v in got]
        assert len(want_rows) == len(got_rows), (i, want_rows, got_rows)
        for (s1, e1, v1), (s2, e2, v2) in zip(want_rows, got_rows):
            assert (s1, e1) == (s2, e2), i
            assert v1 == pytest.approx(v2, rel=1e-4), (i, s1, e1)
    p.check_overflow()


def test_aligned_chunk_shape_retune_keeps_results():
    """set_rows_per_chunk / autotune_chunk re-jit the step at a new chunk
    shape without changing ANY emitted result: the generator stream is a
    function of (interval, chunk-row) alone, so re-chunking only regroups
    device work (VERDICT r3 item 3 — the engine owns the sweet spot)."""
    windows = [SlidingWindow(Time, 40, 10)]

    def emit(p):
        p.reset()
        outs = p.run(4, collect=True)
        rows = []
        for o in outs:
            rows += [(s, e, float(v[0]))
                     for s, e, c, v in p.lowered_results(o)]
        p.check_overflow()
        return rows

    def same(a, b):
        # per-row tuple streams are bit-identical across chunk shapes, but
        # XLA may tile the f32 row reduction differently → last-ulp sums
        return len(a) == len(b) and all(
            (s1, e1) == (s2, e2) and v1 == pytest.approx(v2, rel=1e-5)
            for (s1, e1, v1), (s2, e2, v2) in zip(a, b))

    p = AlignedStreamPipeline(
        windows, [SumAggregation()], config=CFG,
        throughput=40_000, wm_period_ms=80, seed=3, gc_every=10 ** 9)
    cands = p.chunk_candidates()
    assert p.rows_per_chunk == cands[0]       # heuristic pick = largest
    assert len(cands) >= 2                    # S=8 rows → several divisors
    # record the d each jit TRACE actually sees: jax's cache is keyed on
    # the function object, so a stale-trace regression (re-wrapping one
    # function) would keep executing the original shape (r4 review)
    traced_ds = []
    orig_impl = p._step_impl

    def spy(state, dm, qs, key, ii, d):
        traced_ds.append(d)
        return orig_impl(state, dm, qs, key, ii, d)

    p._step_impl = spy
    base_rows = emit(p)
    assert base_rows
    for d in cands[1:]:
        p.set_rows_per_chunk(d)
        assert same(emit(p), base_rows), d
        assert traced_ds[-1] == d             # genuinely retraced at d

    timings = p.autotune_chunk(reps=1)
    assert set(timings) == set(cands)
    assert p.rows_per_chunk == min(timings, key=timings.get)
    assert same(emit(p), base_rows)           # winner: same stream/results


def test_sub_row_chunking_differential():
    """Coarse grids (S=1, huge R) exceed the per-chunk lift budget even at
    d=1; the generator then iterates sub-row chunks keyed per absolute
    (row, sub) pair (r5). Forced here with a tiny budget: results must
    match the simulator on the materialized stream, and the sub-chunked
    stream must replay bit-exactly."""
    windows = [SlidingWindow(Time, 200, 100)]
    p = AlignedStreamPipeline(
        windows, [SumAggregation()], config=CFG, throughput=2560,
        wm_period_ms=100, seed=9, gc_every=10 ** 9, max_chunk_elems=64)
    assert p._n_sub > 1, "budget did not force sub-row chunking"
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(1000)
    p.reset()
    for i in range(4):
        out = p.run(1)[0]
        vals, ts = p.materialize_interval(i)
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
        exp = {(w.get_start(), w.get_end()): float(w.get_agg_values()[0])
               for w in sim.process_watermark((i + 1) * 100)
               if w.has_value()}
        got = {(s, e): float(v[0])
               for s, e, c, v in p.lowered_results(out) if c > 0}
        assert set(got) == set(exp), (i, got, exp)
        for k in got:
            assert got[k] == pytest.approx(exp[k], rel=1e-4), (i, k)


def test_steps_clean_under_transfer_guard():
    """ISSUE 9 satellite — the dynamic complement of the host-sync rule:
    after warmup, N aligned steps run under
    ``jax.transfer_guard("disallow")``. The only sanctioned
    host->device movement per interval is the EXPLICIT device_put of
    the interval scalars in FusedPipelineDriver (an implicit transfer
    creeping into the step loop — a numpy operand, a host-forced
    concretization — fails here). The results must still bit-match the
    oracle: the guard proves transfer-cleanliness, the differential
    body proves it didn't change semantics."""
    import jax

    windows = [TumblingWindow(Time, 50)]
    p = AlignedStreamPipeline(
        windows, [SumAggregation()], config=CFG, throughput=20_000,
        wm_period_ms=100, max_lateness=100, seed=5, gc_every=10 ** 9)
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(100)
    p.reset()
    p.run(1)        # warmup: compile outside the guard
    outs = [None]
    with jax.transfer_guard("disallow"):
        outs.extend(p.run(3))
    p.sync()        # drain point: device_get is explicit, outside guard
    for i in range(4):
        vals, ts = p.materialize_interval(i)
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
        exp = {(w.get_start(), w.get_end()): float(w.get_agg_values()[0])
               for w in sim.process_watermark((i + 1) * 100)
               if w.has_value()}
        if outs[i] is None:
            continue
        got = {(s, e): float(v[0])
               for s, e, c, v in p.lowered_results(outs[i]) if c > 0}
        assert set(got) == set(exp), (i, got, exp)
        for k in got:
            assert got[k] == pytest.approx(exp[k], rel=1e-4), (i, k)
    p.check_overflow()
