"""Crash certification for the actuation plane (ISSUE 18 tentpole part
2 + satellite): the PR 8 crash-point-sweep discipline applied to the
FULL retune commit path — every flight-event emit point (including the
``autotune`` begin/retrace/commit events themselves) and every fsio
write/fsync/replace inside the retune's checkpoint bundle (state npz,
config sidecar, the NEW ``geometry.json`` sidecar, delivery ledger,
manifest, pointer) with torn/short/ENOSPC variants — armed one at a
time in a fresh environment, recovered under the Supervisor, and
required to deliver output bit-identical to the uninterrupted oracle
through an EXACTLY_ONCE sink whose collect hook raises on any repeated
``(interval, row)`` tag.

Plus the chaos soak: repeated injected crashes straddling BOTH retune
boundaries on a ManualClock with the degradation ladder live, and the
mesh-serving twin — threading the sensor plane (obs + WorkloadMonitor)
through ``run_supervised_mesh`` never changes delivered output."""

import os

import numpy as np

from scotty_tpu import (SlidingWindow, SumAggregation, TumblingWindow,
                        WindowMeasure)
from scotty_tpu import obs as _obs
from scotty_tpu.autotune import (DegradationLadder, EngineGeometry,
                                 RUNG_BACKPRESSURE, RUNG_NONE,
                                 run_retuned_pipeline)
from scotty_tpu.delivery import EXACTLY_ONCE, TransactionalSink
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.pipeline import AlignedStreamPipeline
from scotty_tpu.mesh_serving import MeshQueryService, run_supervised_mesh
from scotty_tpu.obs.server import HealthPolicy
from scotty_tpu.resilience import ChaosError, ManualClock, Supervisor
from scotty_tpu.resilience.chaos import CrashPlan, crash_point_sweep
from scotty_tpu.serving import QueryAdmission

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


def pipeline_factory(config=None):
    return AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()],
        config=config or CFG, throughput=20_000, wm_period_ms=100,
        max_lateness=100, seed=5, gc_every=10 ** 9, value_scale=1024.0)


#: the retune under test: a batch-span move PLUS a shape-neutral shaper
#: knob — the delta class that exercises retrace, transplant padding and
#: the full geometry sidecar (not just the EngineConfig half)
_BASE = EngineGeometry.from_pipeline(pipeline_factory())
_BIG = _BASE.replace(batch_size=512, late_capacity=512)
_SMALL = _BASE.replace(batch_size=128)


def _fresh_dir(tmp_path, counter=[0]):
    counter[0] += 1
    d = os.path.join(str(tmp_path), f"env{counter[0]}")
    os.makedirs(d, exist_ok=True)
    return d


def _retune_env_factory(tmp_path, schedule, n_intervals):
    """make_env for the sweep: a supervised aligned pipeline whose
    checkpoint at the scheduled boundaries IS a live retune commit, with
    an exactly-once sink; run() returns the delivered-item stream (the
    downstream consumer's exact view), and the collect hook fails the
    armed run itself on any duplicated (interval, row) tag."""

    def make_env():
        d = _fresh_dir(tmp_path)
        obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=2048))

        def run():
            sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                             obs=obs, checkpoint_every=2, max_restarts=8,
                             seed=3)
            sup.sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
            seen = set()
            delivered = []

            def collect(item):
                tag = (item[0], item[1])
                if tag in seen:
                    raise AssertionError(
                        f"duplicate delivery tag {tag}: exactly-once "
                        f"broken across the retune commit")
                seen.add(tag)
                delivered.append(item)

            run_retuned_pipeline(pipeline_factory, n_intervals, sup,
                                 schedule=dict(schedule),
                                 collect=collect)
            return delivered

        return obs, run

    return make_env


def _assert_green(report, min_sites=1):
    assert report.sites >= min_sites
    assert report.fired == report.ran       # every armed site was reached
    assert report.oracle_len > 0
    assert report.failures == [], (
        f"{len(report.failures)} of {report.ran} crash sites broke the "
        f"retune commit's exactly-once twin — first: {report.failures[0]}")


# -- site enumeration sanity -------------------------------------------------

def test_enumeration_covers_retune_commit_sites(tmp_path):
    """The site list spans the whole retune story: the autotune
    begin/retrace/commit flight events are themselves armable crash
    sites, and the committed bundle's NEW geometry.json sidecar is an
    fsio site with fault variants — alongside the ledger and the seal."""
    make_env = _retune_env_factory(tmp_path, {2: _BIG}, n_intervals=4)
    obs, run = make_env()
    sites = CrashPlan().record(obs, run)
    assert len(sites) >= 40
    flight = [s for s in sites if s.domain == "flight"]
    autotune = {s.name for s in flight if s.kind == "autotune"}
    assert {"begin", "retrace", "commit"} <= autotune
    fs_names = {s.name for s in sites if s.domain == "fs"}
    assert "geometry.json" in fs_names       # the knob vector is a site
    assert "ledger.json" in fs_names
    assert "MANIFEST.json" in fs_names
    geo = [s for s in sites if s.domain == "fs"
           and s.name == "geometry.json"]
    assert {s.fault for s in geo if s.kind == "write"} \
        == {"crash", "torn", "short", "enospc"}


# -- the sweeps --------------------------------------------------------------

def test_retune_commit_path_every_site_exactly_once(tmp_path):
    """The headline certification: crash at EVERY enumerated site of a
    run whose interval-2 checkpoint is a live batch-span retune —
    recovery must neither lose, double, nor half-apply the retune at any
    of them (crash before the seal replays and re-applies; crash after
    resumes past it at the committed geometry)."""
    report = crash_point_sweep(
        _retune_env_factory(tmp_path, {2: _BIG}, n_intervals=4))
    _assert_green(report, min_sites=40)


def test_two_retune_schedule_sampled_sites(tmp_path):
    """Sampled sweep over a DOUBLE retune (span up at 2, back down at
    4): sites in the second retune's commit arm against a pipeline that
    is itself the product of a retune — the stacked-retune path."""
    report = crash_point_sweep(
        _retune_env_factory(tmp_path, {2: _BIG, 4: _SMALL},
                            n_intervals=6),
        sample_every=5)
    _assert_green(report, min_sites=60)


# -- chaos soak --------------------------------------------------------------

def test_chaos_soak_crashes_straddling_retunes(tmp_path):
    """Injected crashes at positions 1, 3 and 5 straddle both scheduled
    retunes (at 2 and 4): before the first, between the two, after the
    second. Every restart restores at the committed geometry, the run
    bit-matches the never-crashed plain-pipeline oracle, delivery stays
    exactly-once, and the supervisor ends at the final geometry."""
    obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=2048))
    sup = Supervisor(os.path.join(str(tmp_path), "ck"),
                     clock=ManualClock(), obs=obs, checkpoint_every=2,
                     max_restarts=8, seed=3)
    sup.sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
    crash_at = {1, 3, 5}
    fired = []

    def fault(pos):
        if pos in crash_at:
            crash_at.remove(pos)
            fired.append(pos)
            raise ChaosError(f"chaos @ {pos}")

    seen = set()

    def collect(item):
        tag = (item[0], item[1])
        assert tag not in seen, f"duplicate delivery {tag}"
        seen.add(tag)

    rows = run_retuned_pipeline(pipeline_factory, 6, sup,
                                schedule={2: _BIG, 4: _SMALL},
                                fault=fault, collect=collect)
    ref = pipeline_factory()
    assert rows == [ref.lowered_results(o) for o in ref.run(6)]
    assert fired == [1, 3, 5]
    assert sup.geometry == _SMALL
    assert len(seen) == sum(len(r) for r in rows)
    snap = obs.registry.snapshot()
    assert snap["autotune_retunes"] == 2
    # each crash replays the uncommitted tail; those re-emissions are
    # exactly the duplicates the sink must swallow, not deliver
    assert snap["delivery_duplicates_suppressed"] > 0


def test_ladder_soak_survivors_replay_bit_exact(tmp_path):
    """Chaos soak for the shedding side: a seeded 48-step offered-load
    storm (rate spike + lateness burst) drives the ladder through every
    rung up to backpressure and back to rung 0. The kept-survivor masks
    must replay bit-identically through a fresh ladder fed the same
    stream, conservation must hold exactly at every step, and /healthz
    must go unhealthy while a rung is active and recover at rung 0."""
    rng = np.random.default_rng(7)
    steps = []
    for s in range(48):
        rate = 2000 if 16 <= s < 32 else 200
        late_frac = 0.5 if 24 <= s < 36 else 0.05
        n = rng.poisson(rate)
        ts = np.sort(rng.integers(0, 1000, size=n)) + s * 1000
        late = rng.random(n) < late_frac
        ts = np.where(late, ts - 1500, ts)
        steps.append(ts)

    def drive(obs=None):
        lad = DegradationLadder(sample_mod=4, relax_after=3, obs=obs)
        policy = HealthPolicy()
        masks, rungs = [], []
        saw_unhealthy = False
        for s, ts in enumerate(steps):
            keep = lad.admit(ts, watermark=s * 1000)
            assert lad.conserved, f"offered != admitted + shed at {s}"
            masks.append(np.asarray(keep).copy())
            lad.audit(budget=400.0)
            rungs.append(lad.rung)
            if obs is not None and lad.rung > RUNG_NONE:
                v = policy.verdict(obs)
                assert not v["healthy"]
                assert v["checks"]["degradation"] == {
                    "ok": False, "active_rung": float(lad.rung)}
                saw_unhealthy = True
        return lad, masks, rungs, saw_unhealthy

    obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=2048))
    lad, masks, rungs, saw_unhealthy = drive(obs)
    assert max(rungs) == RUNG_BACKPRESSURE   # the storm hit the top rung
    assert rungs[-1] == RUNG_NONE            # ...and fully recovered
    assert saw_unhealthy
    v = HealthPolicy().verdict(obs)
    assert v["healthy"] and v["checks"]["degradation"]["ok"]
    assert obs.registry.snapshot()["degrade_shed_tuples"] == lad.shed > 0
    # bit-exact replay: same stream, fresh ladder, identical survivors
    _, masks2, rungs2, _ = drive()
    assert rungs == rungs2
    assert all(np.array_equal(a, b) for a, b in zip(masks, masks2))


# -- mesh-serving twin (satellite: sensor plane through the mesh loop) -------

_MESH_CFG = EngineConfig(capacity=64, annex_capacity=8, min_trigger_pad=32)
_MESH_CELL = [0]


def _mesh_delivered(tmp_path, name, obs):
    d = os.path.join(str(tmp_path), name)
    os.makedirs(d, exist_ok=True)

    def make_service(shards):
        return MeshQueryService(
            [SumAggregation()], slice_grid=500, max_window_size=4000,
            n_keys=16, n_shards=shards, throughput=16_000,
            wm_period_ms=1000, max_lateness=1000, seed=3,
            config=_MESH_CFG, admission=QueryAdmission(max_queries=8),
            windows=[TumblingWindow(Time, 1000)], obs=obs,
            trace_cell=_MESH_CELL)

    sup = Supervisor(os.path.join(d, "ck"), clock=ManualClock(),
                     obs=obs, max_restarts=4, seed=11)
    churn = {0: [("register", SlidingWindow(Time, 2000, 500), "acme")]}
    return run_supervised_mesh(
        make_service, 3, sup, sink=TransactionalSink(mode=EXACTLY_ONCE),
        churn=churn, reshard_at={1: 4}, initial_shards=8,
        checkpoint_every=2, obs=obs)


def test_mesh_sensor_plane_never_changes_delivery(tmp_path):
    """The mesh loop's obs threading (ISSUE 18 satellite) is a pure
    observer: a churned + resharded supervised mesh run with the full
    sensor plane attached (flight ring + WorkloadMonitor sampled at
    every flight_sync) delivers output identical to the same run with
    no obs at all — and the sensor plane actually recorded."""
    plain = _mesh_delivered(tmp_path, "plain", obs=None)
    obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=4096))
    obs.attach_workload(clock=ManualClock(), audit_interval_s=1.0)
    sensed = _mesh_delivered(tmp_path, "sensed", obs=obs)
    assert sensed == plain and len(plain) > 0
    assert obs.flight.events()               # the ring saw the run
    assert HealthPolicy().verdict(obs)["healthy"]
