"""Differential tests for the fused count-measure pipeline.

Oracles, per semantics domain:

* **In-order** (count-only and count+time mixes): the host simulator —
  the reference calculus replayed class-for-class
  (simulator/operator.py). Exact match expected.
* **Out-of-order**: the device engine (`TpuWindowOperator`) is the
  oracle. The simulator mirrors the reference's TreeSet record-set
  dedup at EQUAL timestamps (StreamRecord equals-ignores-element,
  simulator/slices.py:18-21 — a reproduced reference artifact), which
  the engine's record buffer deliberately does not reproduce (every
  record is kept; PARITY.md). The pipeline must agree with the ENGINE;
  where the fuzz stream has all-distinct ts the simulator agrees too
  and is asserted as a third face.

Cadence quirks pinned here (reference behavior, see the module
docstring of engine/count_pipeline.py): the ends<=cend+1 early-partial
emission, its complete re-emission next watermark, and the lost-window
behavior of last_count jumping to the running total.
"""

import numpy as np
import pytest

import jax

from scotty_tpu import (
    MaxAggregation,
    MeanAggregation,
    SlicingWindowOperator,
    SumAggregation,
    TumblingWindow,
    SlidingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.engine.count_pipeline import CountStreamPipeline

Count, Time = WindowMeasure.Count, WindowMeasure.Time

SMALL = EngineConfig(capacity=1 << 12, batch_size=64, annex_capacity=256,
                     min_trigger_pad=32, record_capacity=1 << 12)


def lowered(agg, part_row, cnt):
    """Host-lower one window's partial row the way the bench edge does."""
    sp = agg.device_spec()
    return float(np.asarray(
        sp.lower(np.asarray(part_row)[None, :], np.asarray([cnt]))[0]))


def pipeline_windows(p, fetched, agg, n_iv):
    """[(start, end, value)] per interval from the fused step outputs."""
    out = []
    for i in range(n_iv):
        ws, we, cnt, res = fetched[i]
        rows = [(int(ws[j]), int(we[j]),
                 lowered(agg, res[0][j], int(cnt[j])))
                for j in range(len(ws)) if cnt[j] > 0]
        out.append(sorted(rows))
    return out


def oracle_windows(make_op, p, agg, n_iv):
    """Replay the pipeline's materialized stream through an operator."""
    op = make_op()
    out = []
    for i in range(n_iv):
        vs, ts = p.materialize_interval(i)
        for v, t in zip(vs, ts):
            op.process_element(float(v), int(t))
        rows = [(w.start, w.end, float(w.agg_values[0]))
                for w in op.process_watermark((i + 1) * p.wm_period_ms)]
        out.append(sorted(rows))
    return out


def assert_same(ref, got, rtol=3e-4):
    assert len(ref) == len(got)
    for i, (r_rows, g_rows) in enumerate(zip(ref, got)):
        assert [r[:2] for r in r_rows] == [g[:2] for g in g_rows], \
            f"interval {i} bounds: {r_rows} vs {g_rows}"
        for r, g in zip(r_rows, g_rows):
            assert abs(r[2] - g[2]) <= rtol * max(1.0, abs(r[2])), \
                f"interval {i} window {r[:2]}: {r[2]} vs {g[2]}"


def run_pipeline(windows, agg, throughput, ooo, n_iv, P=100, lateness=100,
                 seed=3):
    p = CountStreamPipeline(windows, [agg], throughput=throughput,
                            wm_period_ms=P, max_lateness=lateness,
                            seed=seed, out_of_order_pct=ooo)
    fetched = jax.device_get(p.run(n_iv))
    p.check_overflow()
    return p, pipeline_windows(p, fetched, agg, n_iv)


def oracle_wm(p, i):
    return (i + 1) * p.wm_period_ms


def make_sim(windows, agg, lateness):
    def build():
        op = SlicingWindowOperator()
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(agg)
        op.set_max_lateness(lateness)
        return op
    return build


def make_dev(windows, agg, lateness):
    def build():
        op = TpuWindowOperator(config=SMALL)
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(agg)
        op.set_max_lateness(lateness)
        return op
    return build


@pytest.mark.parametrize("agg", [SumAggregation(), MaxAggregation(),
                                 MeanAggregation()])
def test_count_only_inorder_vs_simulator(agg):
    W = [TumblingWindow(Count, 7)]
    p, got = run_pipeline(W, agg, 2000, 0.0, 6)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 6), got)


def test_count_mix_inorder_vs_simulator():
    agg = SumAggregation()
    W = [TumblingWindow(Count, 7), TumblingWindow(Time, 50)]
    p, got = run_pipeline(W, agg, 2000, 0.0, 6)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 6), got)


def test_count_multi_mix_inorder_vs_simulator():
    agg = SumAggregation()
    W = [TumblingWindow(Count, 13), TumblingWindow(Count, 5),
         SlidingWindow(Time, 60, 20)]
    p, got = run_pipeline(W, agg, 3000, 0.0, 8)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 8), got)


@pytest.mark.parametrize("agg", [SumAggregation(), MaxAggregation()])
def test_count_only_ooo_vs_engine(agg):
    W = [TumblingWindow(Count, 7)]
    p, got = run_pipeline(W, agg, 2000, 0.3, 5)
    assert_same(oracle_windows(make_dev(W, agg, 100), p, agg, 5), got)


def test_count_mix_ooo_vs_engine():
    agg = SumAggregation()
    W = [TumblingWindow(Count, 7), TumblingWindow(Time, 50)]
    p, got = run_pipeline(W, agg, 2000, 0.3, 5)
    assert_same(oracle_windows(make_dev(W, agg, 100), p, agg, 5), got)


def test_count_ooo_multi_interval_lateness_vs_engine():
    """Lateness spanning multiple intervals (q = 2): late appends reach
    two interval generations back; the engine's record merge is the
    rank-semantics oracle."""
    agg = SumAggregation()
    W = [TumblingWindow(Count, 11)]
    p, got = run_pipeline(W, agg, 2000, 0.2, 6, lateness=200)
    assert_same(oracle_windows(make_dev(W, agg, 200), p, agg, 6), got)


def test_count_inorder_three_way():
    """In-order streams have no ripple and (at u=1) no equal-ts ties, so
    the simulator, the device engine, and the fused pipeline must agree
    exactly."""
    agg = SumAggregation()
    W = [TumblingWindow(Count, 5)]
    p = CountStreamPipeline(W, [agg], throughput=1000, wm_period_ms=40,
                            max_lateness=40, seed=0)
    n_iv = 6
    fetched = jax.device_get(p.run(n_iv))
    p.check_overflow()
    got = pipeline_windows(p, fetched, agg, n_iv)
    assert_same(oracle_windows(make_sim(W, agg, 40), p, agg, n_iv), got)
    assert_same(oracle_windows(make_dev(W, agg, 40), p, agg, n_iv), got)


def test_early_partial_and_reemission_quirk():
    """ends <= cend+1: with R_total=13 and c=7, interval 0 ends at
    N=13 so window [7,14) (end == N+1) emits one tuple early with a
    PARTIAL value (ranks [7,13)), and interval 1 re-emits it complete —
    the reference's off-by-one, reproduced."""
    agg = SumAggregation()
    W = [TumblingWindow(Count, 7)]
    p, got = run_pipeline(W, agg, 1000, 0.0, 2, P=13, lateness=13)
    iv0 = dict((tuple(r[:2]), r[2]) for r in got[0])
    iv1 = dict((tuple(r[:2]), r[2]) for r in got[1])
    assert (7, 14) in iv0 and (7, 14) in iv1          # partial then full
    vs0, _ = p.materialize_interval(0)
    vs1, _ = p.materialize_interval(1)
    allv = np.concatenate([vs0, vs1])
    np.testing.assert_allclose(iv0[(7, 14)], float(np.sum(vs0[7:13])),
                               rtol=1e-5)
    np.testing.assert_allclose(iv1[(7, 14)], float(np.sum(allv[7:14])),
                               rtol=1e-5)


def test_rejects_unsupported_specs():
    from scotty_tpu import SessionWindow
    from scotty_tpu.core.aggregates import QuantileAggregation

    with pytest.raises(NotImplementedError):
        CountStreamPipeline([TumblingWindow(Time, 100)], [SumAggregation()])
    with pytest.raises(NotImplementedError):
        CountStreamPipeline([SessionWindow(Time, 10)], [SumAggregation()])
    with pytest.raises(NotImplementedError):
        CountStreamPipeline([TumblingWindow(Count, 10)],
                            [QuantileAggregation(0.5)])


def test_unsupported_error_names_rank_range_classes():
    """ISSUE 11 satellite: the rejection messages name the rank-range
    classes the pipeline DOES support and the sliding-count entry
    point, instead of a bare refusal."""
    from scotty_tpu import SessionWindow

    with pytest.raises(NotImplementedError) as ei:
        CountStreamPipeline([SessionWindow(Count, 10)], [SumAggregation()])
    msg = str(ei.value)
    assert "CountTumbling" in msg and "CountSliding" in msg
    with pytest.raises(NotImplementedError) as ei:
        CountStreamPipeline([TumblingWindow(Time, 100)], [SumAggregation()])
    assert "CountSliding" in str(ei.value)


# ---------------------------------------------------------------------------
# sliding count-measure windows (ISSUE 11)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("size,slide", [(20, 5), (13, 5), (8, 8)])
def test_count_sliding_inorder_vs_simulator(size, slide):
    """Sliding count windows at several overlap ratios (divisible,
    non-divisible, slide == size — which must keep the SLIDING walk's
    end <= cend+2 guard, not collapse into tumbling) vs the reference
    simulator."""
    agg = SumAggregation()
    W = [SlidingWindow(Count, size, slide)]
    p, got = run_pipeline(W, agg, 2000, 0.0, 5)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 5), got)


def test_count_sliding_tumbling_mix_inorder_vs_simulator():
    agg = SumAggregation()
    W = [SlidingWindow(Count, 20, 5), TumblingWindow(Count, 7)]
    p, got = run_pipeline(W, agg, 2000, 0.0, 5)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 5), got)


def test_count_sliding_time_mix_inorder_vs_simulator():
    agg = SumAggregation()
    W = [SlidingWindow(Count, 15, 5), TumblingWindow(Time, 50)]
    p, got = run_pipeline(W, agg, 2000, 0.0, 5)
    assert_same(oracle_windows(make_sim(W, agg, 100), p, agg, 5), got)


@pytest.mark.parametrize("agg", [SumAggregation(), MaxAggregation()])
def test_count_sliding_ooo_vs_engine(agg):
    """The OOO arm: sliding rank ranges answered from the stratified
    late rows, vs the engine's record-merge rank semantics."""
    W = [SlidingWindow(Count, 20, 5)]
    p, got = run_pipeline(W, agg, 2000, 0.3, 5)
    assert_same(oracle_windows(make_dev(W, agg, 100), p, agg, 5), got)


# ---------------------------------------------------------------------------
# max_lateness >= wm_period relaxation (ISSUE 11)
# ---------------------------------------------------------------------------


def test_count_ooo_sub_period_lateness_vs_engine():
    """max_lateness < wm_period used to be rejected outright; the
    partial-stratum late model now carries it — vs the engine's record
    merge on the same materialized stream."""
    agg = SumAggregation()
    W = [TumblingWindow(Count, 7)]
    p, got = run_pipeline(W, agg, 2000, 0.25, 5, lateness=40)
    assert p.rem == 40 and p.q == 1 and p.q_full == 0
    assert_same(oracle_windows(make_dev(W, agg, 40), p, agg, 5), got)


def test_count_sliding_ooo_sub_period_lateness_vs_engine():
    agg = SumAggregation()
    W = [SlidingWindow(Count, 20, 5)]
    p, got = run_pipeline(W, agg, 2000, 0.25, 5, lateness=60)
    assert p.rem == 60
    assert_same(oracle_windows(make_dev(W, agg, 60), p, agg, 5), got)


def test_count_ooo_fractional_period_lateness_vs_engine():
    """Lateness between one and two periods (q_full=1 + a partial
    oldest stratum) — the mixed whole/partial band accounting."""
    agg = SumAggregation()
    W = [TumblingWindow(Count, 11)]
    p, got = run_pipeline(W, agg, 2000, 0.2, 6, lateness=150)
    assert p.q_full == 1 and p.rem == 50 and p.q == 2
    assert_same(oracle_windows(make_dev(W, agg, 150), p, agg, 6), got)


def test_relaxed_lateness_counter_gated():
    """The relaxed retention model surfaces through the gated
    count_lateness_relaxed_rows counter (obs diff DEFAULT_THRESHOLDS)."""
    from scotty_tpu import obs as _obs

    agg = SumAggregation()
    p = CountStreamPipeline([TumblingWindow(Count, 7)], [agg],
                            throughput=2000, wm_period_ms=100,
                            max_lateness=40, seed=1, out_of_order_pct=0.2)
    o = _obs.Observability()
    p.reset()
    p.set_observability(o)
    list(p.run(3))
    p.check_overflow()
    assert o.registry.counter(
        _obs.COUNT_LATENESS_RELAXED_ROWS).value > 0
    from scotty_tpu.obs.diff import DEFAULT_THRESHOLDS

    assert _obs.COUNT_LATENESS_RELAXED_ROWS in DEFAULT_THRESHOLDS["metrics"]


def test_no_overflow_on_contract_streams():
    """The row-window retention model covers every in-contract trigger:
    the overflow flag stays clear over a multi-interval run."""
    p = CountStreamPipeline([TumblingWindow(Count, 7)], [SumAggregation()],
                            throughput=2000, wm_period_ms=100,
                            max_lateness=100, seed=0, out_of_order_pct=0.2)
    p.reset()
    p.run(5, collect=False)
    assert not bool(jax.device_get(p.state.overflow))


def test_count_steps_clean_under_transfer_guard():
    """ISSUE 9 satellite: warmed count-measure steps dispatch with zero
    implicit transfers under jax.transfer_guard("disallow") and the
    emitted windows bit-match the per-record oracle replay."""
    agg = SumAggregation()
    windows = [TumblingWindow(Count, 7)]
    p = CountStreamPipeline(windows, [agg], throughput=2000,
                            wm_period_ms=100, max_lateness=100, seed=0,
                            out_of_order_pct=0.2)
    p.reset()
    outs = list(p.run(1))       # warmup: compile outside the guard
    with jax.transfer_guard("disallow"):
        outs.extend(p.run(4))
    fetched = jax.device_get(outs)
    p.check_overflow()
    got = pipeline_windows(p, fetched, agg, 5)
    ref = oracle_windows(
        make_dev(windows, agg, 100), p, agg, 5)
    assert_same(ref, got)
