"""Host→device ingest pipeline (SURVEY.md §7 stage 7) tests.

Correctness: a host-resident stream through HostFeed's packed
transfer+unpack path must produce the same windows as the simulator.
Transport: the end-to-end host-fed cell must saturate the raw link —
the engine adds (nearly) nothing on top of device_put of the same bytes.
"""

import numpy as np
import pytest

from scotty_tpu import (
    MeanAggregation,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.engine.host_ingest import HostFeed, measure_link

Time = WindowMeasure.Time


def test_host_feed_matches_simulator():
    rng = np.random.default_rng(3)
    B = 256
    windows = [TumblingWindow(Time, 100), SlidingWindow(Time, 300, 100)]
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, batch_size=B, annex_capacity=8,
        min_trigger_pad=32))
    sim = SlicingWindowOperator()
    for o in (op, sim):
        for w in windows:
            o.add_window_assigner(w)
        o.add_aggregation(SumAggregation())
        o.add_aggregation(MeanAggregation())
        o.set_max_lateness(100)
    feed = HostFeed(op)

    next_wm = 100
    for i in range(8):
        lo = i * 130
        ts = np.sort(rng.integers(lo, lo + 130, size=B)).astype(np.int64)
        vals = rng.random(B).astype(np.float32) * 100
        feed.feed(vals, ts)
        sim.process_elements(vals, ts)
        while int(ts[-1]) >= next_wm:
            want = [(w.get_start(), w.get_end(),
                     [float(v) for v in w.get_agg_values()])
                    for w in sim.process_watermark(next_wm)
                    if w.has_value()]
            ws, we, cnt, lowered = op.process_watermark_arrays(next_wm)
            got = [(int(ws[j]), int(we[j]),
                    [float(lw[j]) for lw in lowered])
                   for j in range(ws.shape[0]) if cnt[j] > 0]
            assert [(s, e) for s, e, _ in want] == \
                   [(s, e) for s, e, _ in got], next_wm
            for (_, _, a), (_, _, b) in zip(want, got):
                for x, y in zip(a, b):
                    # f32 device accumulation vs the f64 host oracle
                    assert x == pytest.approx(y, rel=2e-3), next_wm
            next_wm += 100
    op.check_overflow()


def test_host_feed_delta_packing_roundtrip():
    ts = np.asarray([5, 5, 7, 1000, 10**7], np.int64) + 3_000_000_000_000
    vals = np.arange(5, dtype=np.float32)
    base, deltas, v = HostFeed.pack(vals, ts)
    assert deltas.dtype == np.uint32
    assert (base + deltas.astype(np.int64) == ts).all()


def test_host_fed_cell_saturates_link():
    """End-to-end host-fed throughput must reach a meaningful fraction of
    the raw device_put bandwidth of the same packed bytes — the pipeline
    is transport-bound by design (BASELINE.md's host-fed row reports the
    same two numbers from the TPU run)."""
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_host_fed_cell

    import jax

    cfg = BenchmarkConfig(name="hf", throughput=1 << 17, runtime_s=4,
                          batch_size=1 << 14, capacity=1 << 12,
                          watermark_period_ms=1000)
    r = run_host_fed_cell(cfg, "Tumbling(1000)", "sum")
    assert r.n_windows_emitted > 0
    assert r.link_mbps_raw > 0
    assert r.link_saturation > 0
    if jax.devices()[0].platform != "cpu":
        # generous bound: transfers + unpack + ingest should not cost more
        # than ~3x the bare link (the tunnel run in BASELINE.md lands near
        # 1x). Only meaningful where the link IS the bottleneck: on the
        # CPU backend "transfer" is a ~250 MB/s in-process memcpy while
        # ingest compute bounds the region, so saturation is inherently
        # tiny there (this test sat unreported behind the pre-PR2
        # checkpoint abort — the bound never held on CPU).
        assert r.link_saturation > 0.3, (r.link_saturation, r.link_mbps_raw)


def test_keyed_host_feed_matches_per_key_results():
    """KeyedHostFeed packs (key, value, ts) records into padded [K, Bk]
    rounds; results must equal per-key host operators fed the same tuples
    (VERDICT r3 item 7 — the keyed host boundary end to end)."""
    import numpy as np

    from scotty_tpu import SlicingWindowOperator, SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.host_ingest import KeyedHostFeed
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    K, Bk = 4, 64
    rng = np.random.default_rng(5)
    N = 300
    ts = np.sort(rng.integers(0, 5000, size=N)).astype(np.int64)
    keys = rng.integers(0, K, size=N).astype(np.int64)
    vals = rng.random(N).astype(np.float32)

    op = KeyedTpuWindowOperator(K, config=EngineConfig(
        capacity=1 << 10, batch_size=Bk, min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    feed = KeyedHostFeed(op)
    for lo in range(0, N, 150):
        sl = slice(lo, lo + 150)
        feed.feed(keys[sl], vals[sl], ts[sl])
    ws, we, cnt, lowered = op.process_watermark_arrays(6000)

    sims = [SlicingWindowOperator() for _ in range(K)]
    for s in sims:
        s.add_window_assigner(TumblingWindow(WindowMeasure.Time, 1000))
        s.add_aggregation(SumAggregation())
        s.set_max_lateness(1000)
    for k, v, t in zip(keys, vals, ts):
        sims[k].process_element(float(v), int(t))
    for k in range(K):
        want = {(w.get_start(), w.get_end()): float(w.get_agg_values()[0])
                for w in sims[k].process_watermark(6000) if w.has_value()}
        got = {(int(s), int(e)): float(v)
               for s, e, c, v in zip(ws, we, cnt[k], lowered[0][k])
               if c > 0}
        assert got == pytest.approx(want), (k, want, got)


def test_keyed_host_feed_rejects_out_of_range_keys():
    """ADVICE r4 (low): keys outside [0, K) get a clear contract error,
    not an opaque broadcast failure from bincount."""
    import pytest

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.host_ingest import KeyedHostFeed
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    op = KeyedTpuWindowOperator(4, config=EngineConfig(
        capacity=1 << 8, batch_size=8, min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 100))
    op.add_aggregation(SumAggregation())
    feed = KeyedHostFeed(op)
    ts = np.arange(3, dtype=np.int64)
    vals = np.ones(3, np.float32)
    with pytest.raises(ValueError, match="out of range"):
        feed.pack(np.array([0, 1, 4]), vals, ts)
    with pytest.raises(ValueError, match="out of range"):
        feed.pack(np.array([-1, 1, 2]), vals, ts)
    # ISSUE 5 satellite: a round holding BOTH negative and >= K keys must
    # report both offending value classes plus the out-of-range count —
    # the old single-value message picked whichever end it checked first
    with pytest.raises(ValueError) as exc:
        feed.pack(np.array([-3, 9, 1]), vals, ts)
    msg = str(exc.value)
    assert "-3" in msg and "9" in msg
    assert "2 tuple(s)" in msg
