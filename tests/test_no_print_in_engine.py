"""Tier-1 lint: the engine core stays silent (ISSUE 1 satellite; extended
to connectors/ and bench/ in ISSUE 2, serving/ in ISSUE 6, ingest/ and
soak/ in ISSUE 7, delivery/ in ISSUE 8), nothing sleeps on the wall
clock outside the injectable-clock module (ISSUE 3 satellite;
serving/ingest/soak are covered by the all-of-scotty_tpu sweep), and the
obs/ingest/soak/delivery layers never read the wall clock directly
(ISSUE 4 satellite, extended in ISSUES 7/8 — a soak that timed its
audits on a bare ``time.time()``, or a delivery ledger that stamped
epochs off the wall clock, could never run deterministically on a
ManualClock).

The reference's engine never logs — its only output was the benchmark-side
throughput logger (SURVEY.md §5). The port preserves that discipline: all
output from ``scotty_tpu/engine/``, ``scotty_tpu/core/``,
``scotty_tpu/connectors/`` and ``scotty_tpu/bench/`` must flow through the
metrics registry / overridable echo sinks (scotty_tpu.obs), never a bare
``print(`` — bench output in particular must stay capturable so the
``obs diff`` gate and tests can consume it. AST-based so strings/comments
mentioning print don't trip it.

The sleep lint covers ALL of ``scotty_tpu/``: every backoff/watchdog wait
must go through :mod:`scotty_tpu.resilience.clock` (the one exempt
module), so chaos tests can drive recovery deterministically with a
ManualClock — a bare ``time.sleep`` anywhere would reintroduce
wall-clock nondeterminism into the resilience paths.
"""

import ast
import pathlib

import scotty_tpu

PKG_ROOT = pathlib.Path(scotty_tpu.__file__).parent
SILENT_DIRS = ("engine", "core", "connectors", "bench", "serving",
               "ingest", "soak", "delivery")
#: packages whose wall-clock reads must route through resilience.clock
#: (wall_time / the injectable Clock); time.perf_counter stays allowed
WALLTIME_DIRS = ("obs", "ingest", "soak", "delivery")
#: the single module allowed to call time.sleep (SystemClock lives there)
SLEEP_EXEMPT = PKG_ROOT / "resilience" / "clock.py"


def _print_calls(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield f"{path}:{node.lineno}"


def test_engine_core_have_no_bare_print():
    offenders = []
    for d in SILENT_DIRS:
        for path in sorted((PKG_ROOT / d).rglob("*.py")):
            offenders.extend(_print_calls(path))
    assert not offenders, (
        "bare print( in the silent engine core — route output through "
        "the scotty_tpu.obs registry/sinks instead: "
        + ", ".join(offenders))


def _sleep_calls(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # time.sleep(...)
        if (isinstance(f, ast.Attribute) and f.attr == "sleep"
                and isinstance(f.value, ast.Name)
                and f.value.id == "time"):
            yield f"{path}:{node.lineno}"
        # from time import sleep; sleep(...)
        elif isinstance(f, ast.Name) and f.id == "sleep":
            yield f"{path}:{node.lineno}"


def test_no_bare_time_sleep():
    """All waits go through the injectable clock
    (scotty_tpu.resilience.clock) so backoff/watchdog logic stays
    deterministic under chaos tests; ``asyncio.sleep``/``Clock.sleep``
    calls are fine — only the wall-clock ``time.sleep`` (and a bare
    imported ``sleep``) are rejected, everywhere but clock.py itself."""
    offenders = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        if path == SLEEP_EXEMPT:
            continue
        offenders.extend(_sleep_calls(path))
    assert not offenders, (
        "bare time.sleep in scotty_tpu — route waits through "
        "scotty_tpu.resilience.clock (injectable Clock): "
        + ", ".join(offenders))


def _walltime_calls(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        # time.time(...) / time.monotonic(...)
        if (isinstance(f, ast.Attribute)
                and f.attr in ("time", "monotonic")
                and isinstance(f.value, ast.Name)
                and f.value.id == "time"):
            yield f"{path}:{node.lineno}"
        # from time import time/monotonic; time(...) / monotonic(...)
        elif isinstance(f, ast.Name) and f.id in ("time", "monotonic"):
            yield f"{path}:{node.lineno}"


def test_no_bare_walltime_in_obs():
    """ISSUE 4 satellite, mirroring the no-bare-sleep rule (extended over
    ``ingest/`` and ``soak/`` in ISSUE 7): flight recorder / postmortem /
    export timestamps — and every soak pace/audit/watchdog read — must
    come from the injectable clock (``resilience.clock.Clock`` for
    monotonic event time, ``resilience.clock.wall_time`` for export
    rows) — never a bare ``time.time()``/``time.monotonic()`` — so chaos
    tests can drive the whole operational layer on a ManualClock and
    bundle timelines stay deterministic. ``time.perf_counter`` (relative
    span durations) stays allowed."""
    offenders = []
    for d in WALLTIME_DIRS:
        for path in sorted((PKG_ROOT / d).rglob("*.py")):
            offenders.extend(_walltime_calls(path))
    assert not offenders, (
        "bare time.time()/time.monotonic() in scotty_tpu/{obs,ingest,"
        "soak}/ — route timestamps through scotty_tpu.resilience.clock "
        "(injectable Clock / wall_time): " + ", ".join(offenders))
