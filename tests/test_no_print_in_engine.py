"""Tier-1 hygiene lints, now driven by the analysis rules (ISSUE 9
satellite): the three grown-by-accretion AST walkers this file used to
carry (no-print since ISSUE 1, no-sleep since ISSUE 3, no-wall-clock
since ISSUE 4, each re-extended by hand in ISSUES 2/6/7/8) collapsed
into one parametrized test over :mod:`scotty_tpu.analysis`. Extending
a scope is now a one-line ``include``/``exclude`` change on the rule
class in scotty_tpu/analysis/rules/hygiene.py — and the rules' firing
behavior is itself proven by the seeded corpus
(tests/analysis_corpus/, tests/test_analysis.py).

Kept as a separate file (rather than folded into test_analysis.py's
whole-tree check) so a hygiene regression fails with the rule's name
in the test id, exactly as the old walkers did.
"""

import pytest

from scotty_tpu.analysis import Project, RULES, default_root, run_check

HYGIENE_RULES = ("no-print", "no-sleep", "no-wall-clock")


@pytest.fixture(scope="module")
def project():
    return Project(default_root())


@pytest.mark.parametrize("rule", HYGIENE_RULES)
def test_hygiene_rule_clean_over_package(rule, project):
    new, _, _ = run_check(project, [RULES[rule]])
    assert not new, "\n".join(f.render() for f in new)
