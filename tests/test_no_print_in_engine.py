"""Tier-1 lint: the engine core stays silent (ISSUE 1 satellite; extended
to connectors/ and bench/ in ISSUE 2).

The reference's engine never logs — its only output was the benchmark-side
throughput logger (SURVEY.md §5). The port preserves that discipline: all
output from ``scotty_tpu/engine/``, ``scotty_tpu/core/``,
``scotty_tpu/connectors/`` and ``scotty_tpu/bench/`` must flow through the
metrics registry / overridable echo sinks (scotty_tpu.obs), never a bare
``print(`` — bench output in particular must stay capturable so the
``obs diff`` gate and tests can consume it. AST-based so strings/comments
mentioning print don't trip it.
"""

import ast
import pathlib

import scotty_tpu

PKG_ROOT = pathlib.Path(scotty_tpu.__file__).parent
SILENT_DIRS = ("engine", "core", "connectors", "bench")


def _print_calls(path: pathlib.Path):
    tree = ast.parse(path.read_text(), filename=str(path))
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"):
            yield f"{path}:{node.lineno}"


def test_engine_core_have_no_bare_print():
    offenders = []
    for d in SILENT_DIRS:
        for path in sorted((PKG_ROOT / d).rglob("*.py")):
            offenders.extend(_print_calls(path))
    assert not offenders, (
        "bare print( in the silent engine core — route output through "
        "the scotty_tpu.obs registry/sinks instead: "
        + ", ".join(offenders))
