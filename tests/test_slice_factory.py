"""Eager/lazy decision-table tests — transliterated from
slicing/src/test/.../SliceFactoryTest.java (pins the storage-mode selection
to the general-stream-slicing paper's decision tree)."""

import pytest

from scotty_tpu.core import (
    ForwardContextAware,
    ReduceAggregateFunction,
    SessionWindow,
    WindowMeasure,
)
from scotty_tpu.simulator import (
    EagerSlice,
    Fixed,
    LazyAggregateStore,
    LazySlice,
    SliceFactory,
    WindowManager,
)
from scotty_tpu.state import MemoryStateFactory


class FakeContextWindow(ForwardContextAware):
    def __init__(self, measure):
        self.measure = measure

    def create_context(self):
        return None


@pytest.fixture
def env():
    store = LazyAggregateStore()
    state_factory = MemoryStateFactory()
    window_manager = WindowManager(state_factory, store)
    slice_factory = SliceFactory(window_manager, state_factory)
    window_manager.add_aggregation(ReduceAggregateFunction(lambda a, b: a + b))
    return window_manager, slice_factory


def test_lazy_slice_context_aware(env):
    wm, sf = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    assert wm.get_max_lateness() > 0
    assert wm.has_context_aware_window()
    assert not wm.is_session_window_case()

    assert isinstance(sf.create_slice_now(0, 10, Fixed()), LazySlice)


def test_lazy_slice_count(env):
    wm, sf = env
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Count))

    assert wm.has_count_measure()
    assert isinstance(sf.create_slice_now(0, 10, Fixed()), LazySlice)


def test_eager_slice_session(env):
    wm, sf = env
    wm.add_window_assigner(SessionWindow(WindowMeasure.Time, 1000))

    assert wm.get_max_lateness() > 0
    assert wm.has_context_aware_window()
    assert wm.is_session_window_case()
    assert not wm.has_count_measure()

    assert isinstance(sf.create_slice_now(0, 10, Fixed()), EagerSlice)

    wm.add_window_assigner(SessionWindow(WindowMeasure.Time, 2000))
    assert wm.is_session_window_case()
    assert isinstance(sf.create_slice_now(0, 10, Fixed()), EagerSlice)


def test_lazy_slice_session_plus_context_aware(env):
    wm, sf = env
    wm.add_window_assigner(SessionWindow(WindowMeasure.Time, 1000))
    wm.add_window_assigner(FakeContextWindow(WindowMeasure.Time))

    assert wm.get_max_lateness() > 0
    assert wm.has_context_aware_window()
    assert not wm.is_session_window_case()

    assert isinstance(sf.create_slice_now(0, 10, Fixed()), LazySlice)
