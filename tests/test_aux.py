"""Aux subsystem tests: hybrid backend selection, checkpoint/resume,
metrics, profiling log analysis, benchmark DSL (SURVEY.md §5, §2.5)."""

import numpy as np
import pytest

from scotty_tpu import (
    CountAggregation,
    QuantileAggregation,
    SessionWindow,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.hybrid import HybridWindowOperator

Time = WindowMeasure.Time
Count = WindowMeasure.Count


# ---------------------------------------------------------------------------
# hybrid decision tree (device analogue of SliceFactoryTest, SURVEY.md §4.2)
# ---------------------------------------------------------------------------


def _decide(windows, aggs):
    op = HybridWindowOperator()
    for w in windows:
        op.add_window_assigner(w)
    for a in aggs:
        op.add_aggregation(a)
    return op._device_realizable()


def test_hybrid_picks_device_for_context_free_time():
    assert _decide([TumblingWindow(Time, 10)], [SumAggregation()])
    assert _decide([SlidingWindow(Time, 20, 5), TumblingWindow(Time, 10)],
                   [SumAggregation(), CountAggregation()])


def test_hybrid_picks_device_for_sessions():
    # round 3: device sessions are fully general (engine/sessions.py) —
    # pure, mixed with time-grid windows, in- or out-of-order
    assert _decide([SessionWindow(Time, 10)], [SumAggregation()])
    assert _decide([SessionWindow(Time, 10), TumblingWindow(Time, 40)],
                   [SumAggregation()])


def test_hybrid_picks_host_for_count_measure_sessions():
    assert not _decide([SessionWindow(Count, 10)], [SumAggregation()])


def test_hybrid_picks_device_for_count_only():
    # round 3: count-only workloads run on device (record-buffer rank
    # ranges), in- or out-of-order
    assert _decide([TumblingWindow(Count, 10)], [SumAggregation()])


def test_hybrid_picks_device_for_count_time_mix():
    # round 4: count+time mixes run on device in- AND out-of-order (record
    # rank ranges + arrival-order cut calculus) — no in-order declaration
    # needed (VERDICT r3 item 1)
    assert _decide([TumblingWindow(Count, 10), TumblingWindow(Time, 10)],
                   [SumAggregation()])


def test_hybrid_picks_host_for_host_only_aggregate():
    assert not _decide([TumblingWindow(Time, 10)], [QuantileAggregation(0.5)])


def test_hybrid_runs_host_path_end_to_end():
    op = HybridWindowOperator()
    op.add_window_assigner(SessionWindow(Time, 5))
    op.add_aggregation(QuantileAggregation(0.5))   # host-only aggregate
    op.process_element(1, 0)
    op.process_element(2, 2)
    op.process_element(5, 50)
    assert op.backend == "host"
    res = op.process_watermark(100)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for w in res if w.has_value()]
    assert (0, 7, 2) in wins           # median of {1, 2}


def test_hybrid_runs_device_sessions_end_to_end():
    op = HybridWindowOperator()
    op.add_window_assigner(SessionWindow(Time, 5))
    op.add_aggregation(SumAggregation())
    op.process_element(1, 0)
    op.process_element(2, 2)
    op.process_element(5, 50)
    assert op.backend == "device"
    res = op.process_watermark(100)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for w in res if w.has_value()]
    assert (0, 7, 3) in wins


def test_hybrid_runs_device_path_end_to_end():
    from scotty_tpu.engine import EngineConfig

    op = HybridWindowOperator(engine_config=EngineConfig(
        capacity=512, batch_size=32, annex_capacity=64, min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    for v, t in [(1, 1), (2, 5), (3, 12), (4, 25)]:
        op.process_element(v, t)
    assert op.backend == "device"
    res = op.process_watermark(30)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for w in res if w.has_value()]
    assert (0, 10, 3.0) in wins
    assert (10, 20, 3.0) in wins
    assert (20, 30, 4.0) in wins


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------


def test_engine_checkpoint_roundtrip(tmp_path):
    from scotty_tpu.engine import EngineConfig, TpuWindowOperator
    from scotty_tpu.utils import (restore_engine_operator,
                                  save_engine_operator)

    cfg = EngineConfig(capacity=512, batch_size=32, annex_capacity=64,
                       min_trigger_pad=32)

    def mk():
        op = TpuWindowOperator(config=cfg)
        op.add_window_assigner(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        return op

    a = mk()
    a.process_elements([1, 2, 3], [1, 5, 12])
    a.process_watermark(11)
    save_engine_operator(a, str(tmp_path / "ckpt"))

    b = mk()
    restore_engine_operator(b, str(tmp_path / "ckpt"))
    # continue identically on both
    for op in (a, b):
        op.process_elements([4, 5], [15, 22])
    ra = a.process_watermark(30)
    rb = b.process_watermark(30)
    assert [(w.get_start(), w.get_end(), tuple(w.get_agg_values()))
            for w in ra] == \
        [(w.get_start(), w.get_end(), tuple(w.get_agg_values())) for w in rb]


def test_host_checkpoint_roundtrip(tmp_path):
    from scotty_tpu import SlicingWindowOperator
    from scotty_tpu.utils import restore_host_operator, save_host_operator

    op = SlicingWindowOperator()
    op.add_window_assigner(SessionWindow(Time, 5))
    op.add_aggregation(SumAggregation())
    op.process_element(1, 0)
    op.process_element(2, 2)
    save_host_operator(op, str(tmp_path / "host"))

    op2 = restore_host_operator(str(tmp_path / "host"))
    op2.process_element(5, 50)
    res = op2.process_watermark(100)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for w in res if w.has_value()]
    assert (0, 7, 3) in wins


# ---------------------------------------------------------------------------
# metrics + profiling
# ---------------------------------------------------------------------------


def test_metrics_registry():
    from scotty_tpu.utils import MetricsRegistry, ThroughputLogger

    reg = MetricsRegistry()
    reg.counter("tuples").inc(100)
    reg.gauge("slices").set(42)
    reg.histogram("latency_ms").observe(1.0)
    reg.histogram("latency_ms").observe(9.0)
    snap = reg.snapshot()
    assert snap["tuples"] == 100
    assert snap["slices"] == 42
    assert snap["latency_ms_p99"] >= 1.0

    lines = []
    tl = ThroughputLogger(log_every=10, registry=reg, sink=lines.append)
    tl.observe(5)
    tl.observe(6)
    assert any("elements/second" in s for s in lines)


def test_analyze_log():
    from scotty_tpu.utils import analyze_log

    text = ("x\nThat's 1,000 elements/second/chip\n"
            "That's 3,000 elements/second/chip\n")
    out = analyze_log(text)
    assert out["n"] == 2
    assert out["mean"] == 2000.0


# ---------------------------------------------------------------------------
# benchmark DSL (BenchmarkRunner.java:96-171 parity)
# ---------------------------------------------------------------------------


def test_window_spec_dsl():
    from scotty_tpu.bench import parse_window_spec

    [w] = parse_window_spec("Tumbling(1000)")
    assert isinstance(w, TumblingWindow) and w.size == 1000
    [w] = parse_window_spec("Sliding(60000,1000)")
    assert isinstance(w, SlidingWindow) and (w.size, w.slide) == (60000, 1000)
    [w] = parse_window_spec("Session(500)")
    assert isinstance(w, SessionWindow) and w.gap == 500
    [w] = parse_window_spec("CountTumbling(1000)")
    assert w.measure == Count
    ws = parse_window_spec("randomTumbling(10,1000,20000)")
    assert len(ws) == 10
    assert all(1000 <= w.size < 20000 for w in ws)
    ws2 = parse_window_spec("randomTumbling(10,1000,20000)")
    assert ws == ws2                      # fixed seed, reproducible


def test_bench_generate_batches():
    from scotty_tpu.bench import BenchmarkConfig, generate_batches

    cfg = BenchmarkConfig(throughput=1000, runtime_s=2, batch_size=256)
    batches = generate_batches(cfg)
    assert sum(len(v) for v, _ in batches) >= 1000
    for _, ts in batches:
        assert np.all(np.diff(ts) >= 0)


def test_bench_small_run_device_vs_simulator():
    from scotty_tpu.bench import BenchmarkConfig, run_benchmark

    cfg = BenchmarkConfig(throughput=2000, runtime_s=2, batch_size=128,
                          capacity=1 << 12, watermark_period_ms=500)
    r_dev = run_benchmark(cfg, "Tumbling(100)", "sum", engine="TpuEngine",
                          warmup_batches=1)
    r_sim = run_benchmark(cfg, "Tumbling(100)", "sum", engine="Simulator")
    assert r_dev.n_tuples == r_sim.n_tuples
    # same stream, same windows → same emitted-window count
    assert r_dev.n_windows_emitted == r_sim.n_windows_emitted


def test_hybrid_routes_sessions_to_device():
    """Session workloads run on the engine's device session path with no
    in-order declaration required (round 3: fully general device sessions);
    a forced host backend stays available and agrees."""
    from scotty_tpu.engine import EngineConfig

    cfg = EngineConfig(capacity=512, batch_size=32, annex_capacity=64,
                       min_trigger_pad=32)
    dev = HybridWindowOperator(engine_config=cfg)
    host = HybridWindowOperator(engine_config=cfg, force_backend="host")
    for op in (dev, host):
        op.add_window_assigner(SessionWindow(Time, 5))
        op.add_aggregation(SumAggregation())
        for v, t in [(1, 0), (2, 2), (5, 50), (3, 53)]:
            op.process_element(v, t)
    assert dev.backend == "device"
    assert host.backend == "host"
    rd = [(w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
          for w in dev.process_watermark(100) if w.has_value()]
    rh = [(w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
          for w in host.process_watermark(100) if w.has_value()]
    assert rd == rh == [(0, 7, 3.0), (50, 58, 8.0)]


def test_session_gap_generator_closes_sessions():
    """sessionConfig inserts silent event-time spans so session windows can
    actually complete (LoadGeneratorSource.java:60-76)."""
    import numpy as np

    from scotty_tpu.bench.harness import BenchmarkConfig, generate_batches

    cfg = BenchmarkConfig(throughput=20_000, runtime_s=4, batch_size=4096,
                          session_config={"count": 4, "minGapMs": 1500,
                                          "maxGapMs": 3000})
    ts = np.sort(np.concatenate([b[1] for b in generate_batches(cfg)]))
    assert int(np.diff(ts).max()) >= 1500          # a real silent span
    # without sessionConfig the stream is gap-free at this rate
    cfg2 = BenchmarkConfig(throughput=20_000, runtime_s=4, batch_size=4096)
    ts2 = np.sort(np.concatenate([b[1] for b in generate_batches(cfg2)]))
    assert int(np.diff(ts2).max()) < 1000


def test_engine_checkpoint_preserves_host_clocks(tmp_path):
    """A restored operator must answer the NEXT watermark correctly with no
    new tuples fed — the host clock mirrors (max event time, oldest slice,
    counts) are part of the snapshot."""
    from scotty_tpu.engine import EngineConfig, TpuWindowOperator
    from scotty_tpu.utils.checkpoint import (restore_engine_operator,
                                             save_engine_operator)

    cfg = EngineConfig(capacity=512, batch_size=16, annex_capacity=64,
                       min_trigger_pad=32)

    def build():
        op = TpuWindowOperator(config=cfg)
        op.add_window_assigner(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(100)
        return op

    op = build()
    for v, t in [(1, 1), (2, 5), (3, 12), (4, 25), (5, 33)]:
        op.process_element(v, t)
    save_engine_operator(op, str(tmp_path / "ck"))

    expect = [(w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
              for w in op.process_watermark(40) if w.has_value()]
    assert expect                                # windows actually emit

    op2 = build()
    restore_engine_operator(op2, str(tmp_path / "ck"))
    got = [(w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
           for w in op2.process_watermark(40) if w.has_value()]
    assert got == expect


def test_sketch_lower_device_matches_host():
    """Device-side finalization (DeviceAggregateSpec.lower_device) must
    agree with the host lower for both wide sketches — it is what the
    benchmark latency probes fetch instead of raw [T, width] partials."""
    import jax
    import numpy as np

    from scotty_tpu.core.aggregates import (DDSketchQuantileAggregation,
                                            HyperLogLogAggregation)

    rng = np.random.default_rng(5)
    for agg in (DDSketchQuantileAggregation(0.5), HyperLogLogAggregation(8)):
        spec = agg.device_spec()
        W = spec.width
        if spec.kind == "sum":          # ddsketch: bucket counts
            partials = rng.integers(0, 50, size=(16, W)).astype(np.float32)
        else:                           # hll: register maxima
            partials = rng.integers(0, 20, size=(16, W)).astype(np.float32)
        counts = partials.sum(axis=-1).astype(np.int64)
        want = np.asarray(spec.lower(partials, counts), np.float64)
        got = np.asarray(jax.device_get(
            jax.jit(spec.lower_device)(partials, counts)), np.float64)
        ok = np.isclose(want, got, rtol=1e-3) | (np.isnan(want)
                                                 & np.isnan(got))
        assert ok.all(), (spec.token, want, got)
