"""Metrics registry: snapshot math, bounded reservoir histogram,
thread-safety, ThroughputLogger guards (ISSUE 1 satellites)."""

import threading

import numpy as np

from scotty_tpu.utils.metrics import (
    Histogram,
    MetricsRegistry,
    ThroughputLogger,
)


def test_counter_gauge_snapshot_math():
    reg = MetricsRegistry()
    reg.counter("tuples").inc(100)
    reg.counter("tuples").inc(50)
    reg.gauge("occupancy").set(0.25)
    snap = reg.snapshot()
    assert snap["tuples"] == 150
    assert snap["occupancy"] == 0.25
    assert snap["elapsed_s"] > 0
    assert abs(snap["tuples_per_s"] - 150 / snap["elapsed_s"]) < 1e-6


def test_histogram_exact_when_small():
    reg = MetricsRegistry()
    h = reg.histogram("lat")
    for v in range(100):
        h.observe(float(v))
    assert h.count == 100
    assert h.min == 0.0 and h.max == 99.0
    assert abs(h.mean() - 49.5) < 1e-9
    assert h.percentile(50) == np.percentile(np.arange(100.0), 50)
    snap = reg.snapshot()
    assert snap["lat_count"] == 100
    assert snap["lat_p99"] >= snap["lat_p50"]
    assert snap["lat_max"] == 99.0


def test_histogram_bounded_reservoir():
    h = Histogram(max_samples=512)
    n = 100_000
    for v in range(n):
        h.observe(float(v))
    # memory stays bounded while exact stats stay exact
    assert len(h.samples) == 512
    assert h.count == n
    assert h.min == 0.0 and h.max == float(n - 1)
    assert abs(h.sum - n * (n - 1) / 2) < 1e-3
    # the uniform reservoir keeps percentiles representative
    assert abs(h.percentile(50) - n / 2) < 0.15 * n
    assert h.percentile(99) > h.percentile(50)


def test_histogram_empty_percentile():
    assert Histogram().percentile(99) == 0.0


def test_registry_thread_safety():
    reg = MetricsRegistry()
    N, T = 10_000, 8
    errs = []

    def work():
        try:
            for _ in range(N):
                reg.counter("c").inc()
                reg.histogram("h").observe(1.0)
        except Exception as e:                      # pragma: no cover
            errs.append(e)

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    # snapshot concurrently with the writers — must not raise or see
    # half-built metrics
    for _ in range(50):
        reg.snapshot()
    for t in threads:
        t.join()
    assert not errs
    assert reg.counter("c").value == N * T
    assert reg.histogram("h").count == N * T


def test_throughput_logger_zero_dt_guard(monkeypatch):
    import scotty_tpu.utils.metrics as m

    reg = MetricsRegistry()
    lines = []
    tl = ThroughputLogger(log_every=10, registry=reg, sink=lines.append)
    # freeze the clock: two threshold crossings in the same tick must not
    # divide by zero
    monkeypatch.setattr(m.time, "perf_counter", lambda: tl._t_last)
    tl.observe(10)
    tl.observe(10)
    assert lines == []                      # no rate computable at dt == 0
    assert reg.counter("ingest_tuples").value == 20


def test_throughput_logger_rate_histogram():
    reg = MetricsRegistry()
    lines = []
    tl = ThroughputLogger(log_every=5, registry=reg, sink=lines.append)
    tl.observe(5)
    tl.observe(5)
    assert any("elements/second" in s for s in lines)
    # each interval sample lands in BOTH the last-value gauge and the
    # rate histogram (distinct name: one Prometheus metric name cannot
    # carry two types)
    assert reg.histogram("ingest_rate_hist").count == len(lines)
    assert reg.gauge("ingest_rate").value > 0
