"""Checkpoint integrity + lineage (ISSUE 8): digest manifests sealed at
commit, restore-time verification naming the corrupt file/LEAF and which
half (bundle vs manifest) failed, torn/short/ENOSPC faults injected
through the fsio shim, the Supervisor's lineage fallback past corrupt
generations, retention GC, the startup tmp sweep, and the
``python -m scotty_tpu.obs fsck`` verifier CLI."""

import json
import os
import shutil

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.connectors.base import (AscendingWatermarks,
                                        KeyedScottyWindowOperator)
from scotty_tpu.delivery import EXACTLY_ONCE, TransactionalSink, run_supervised
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator
from scotty_tpu.obs import FlightRecorder, Observability
from scotty_tpu.resilience import ManualClock, Supervisor
from scotty_tpu.utils import fsio
from scotty_tpu.utils.checkpoint import (
    MANIFEST_NAME,
    CheckpointIntegrityError,
    finalize_checkpoint,
    restore_engine_operator,
    save_engine_operator,
    verify_checkpoint,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=256, batch_size=16, annex_capacity=16,
                   min_trigger_pad=32)


@pytest.fixture(autouse=True)
def _no_leftover_fault_hook():
    yield
    fsio.set_fault_hook(None)


def built_operator():
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(100)
    op.process_elements(np.arange(16, dtype=np.float32),
                        np.arange(16, dtype=np.int64) * 10)
    return op


def sealed_bundle(tmp_path, name="b"):
    d = os.path.join(str(tmp_path), name)
    os.makedirs(d, exist_ok=True)
    save_engine_operator(built_operator(), d)
    finalize_checkpoint(d)
    return d


def _flip_bytes(path, offset=12, junk=b"\xde\xad\xbe\xef"):
    with open(path, "r+b") as f:
        f.seek(offset)
        f.write(junk)


# -- verification ------------------------------------------------------------

def test_sealed_bundle_verifies(tmp_path):
    d = sealed_bundle(tmp_path)
    report = verify_checkpoint(d)
    assert report["ok"] is True and report["files"] >= 2


def test_pre_integrity_bundle_is_unverifiable_not_fatal(tmp_path):
    d = sealed_bundle(tmp_path)
    os.remove(os.path.join(d, MANIFEST_NAME))
    report = verify_checkpoint(d)
    assert report["ok"] is None
    assert "no manifest" in report["reason"]
    # ...and restores exactly as before the integrity layer existed
    restore_engine_operator(built_operator(), d)


def test_corrupt_leaf_named_in_error(tmp_path):
    """A bit-flip inside state.npz names the FILE, the corrupt LEAF, the
    half, and the lineage position — not a generic shape error."""
    d = sealed_bundle(tmp_path)
    # flip bytes inside the npz member payload region
    _flip_bytes(os.path.join(d, "state.npz"), offset=200)
    with pytest.raises(CheckpointIntegrityError) as ei:
        verify_checkpoint(d, lineage_pos=2)
    msg = str(ei.value)
    assert "state.npz" in msg
    assert "leaf_" in msg                       # the corrupt leaf isolated
    assert "bundle is the corrupt half" in msg
    assert "lineage position 2" in msg
    assert ei.value.file == "state.npz"
    assert ei.value.leaf is not None
    # the restore path hits the same gate
    with pytest.raises(CheckpointIntegrityError, match="state.npz"):
        restore_engine_operator(built_operator(), d)


def test_truncated_state_reports_torn_short(tmp_path):
    d = sealed_bundle(tmp_path)
    p = os.path.join(d, "state.npz")
    size = os.path.getsize(p)
    with open(p, "r+b") as f:
        f.truncate(size // 2)
    with pytest.raises(CheckpointIntegrityError,
                       match=r"torn/short \(\d+/\d+ bytes\)"):
        verify_checkpoint(d)


def test_torn_manifest_blames_the_manifest_half(tmp_path):
    d = sealed_bundle(tmp_path)
    p = os.path.join(d, MANIFEST_NAME)
    with open(p, "r+b") as f:
        f.truncate(os.path.getsize(p) // 2)
    with pytest.raises(CheckpointIntegrityError,
                       match="manifest is the corrupt half") as ei:
        verify_checkpoint(d)
    assert ei.value.half == "manifest"
    assert "unreadable/torn" in str(ei.value)


def test_tampered_manifest_table_fails_bundle_digest(tmp_path):
    d = sealed_bundle(tmp_path)
    p = os.path.join(d, MANIFEST_NAME)
    with open(p) as f:
        m = json.load(f)
    next(iter(m["files"].values()))["sha256"] = "0" * 64
    with open(p, "w") as f:
        json.dump(m, f)
    with pytest.raises(CheckpointIntegrityError,
                       match="file table was altered after sealing"):
        verify_checkpoint(d)


def test_missing_file_named(tmp_path):
    d = sealed_bundle(tmp_path)
    os.remove(os.path.join(d, "meta.json"))
    with pytest.raises(CheckpointIntegrityError,
                       match="meta.json is missing from the bundle"):
        verify_checkpoint(d)


def test_silent_short_write_cannot_be_blessed(tmp_path):
    """The intent-digest property: a SHORT write through fsio leaves the
    manifest recording what SHOULD be on disk, so the seal itself can
    never bless the corrupt bytes."""
    d = os.path.join(str(tmp_path), "b")
    os.makedirs(d)

    def short_once(op, path):
        if op == "write" and path.endswith("state.npz"):
            return fsio.SHORT
        return None

    fsio.set_fault_hook(short_once)
    try:
        save_engine_operator(built_operator(), d)
    finally:
        fsio.set_fault_hook(None)
    finalize_checkpoint(d)
    with pytest.raises(CheckpointIntegrityError, match="state.npz"):
        verify_checkpoint(d)


def test_enospc_during_save_propagates(tmp_path):
    d = os.path.join(str(tmp_path), "b")
    os.makedirs(d)
    fsio.set_fault_hook(
        lambda op, path: fsio.ENOSPC if op == "write" else None)
    with pytest.raises(OSError, match="injected ENOSPC"):
        save_engine_operator(built_operator(), d)


# -- supervisor lineage ------------------------------------------------------

def make_conn_op(obs=None):
    return KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 100)],
        aggregations=[SumAggregation()],
        watermark_policy=AscendingWatermarks(), obs=obs)


def committed_lineage(tmp_path, obs=None, n=100, every=25):
    """A supervisor dir with several committed generations + a sink."""
    sup = Supervisor(str(tmp_path), clock=ManualClock(), obs=obs,
                     keep_checkpoints=3)
    sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(n)]
    out = run_supervised(records, make_conn_op, sup, sink=sink,
                         checkpoint_every=every, final_watermark=10_000)
    return sup, out


def _gens(d):
    return sorted((n for n in os.listdir(d) if n.startswith("ckpt-")
                   and ".tmp" not in n),
                  key=lambda n: int(n.split("-")[1]))


def test_lineage_gc_bounds_generations(tmp_path):
    sup, _ = committed_lineage(tmp_path)     # 4 commits, keep 3
    assert len(_gens(str(tmp_path))) == 3
    snap = json.load(open(os.path.join(str(tmp_path), "LATEST.json")))
    assert snap["dir"] == _gens(str(tmp_path))[-1]


def test_corrupted_latest_falls_back_to_lineage(tmp_path):
    obs = Observability(flight=FlightRecorder(capacity=256))
    sup, _ = committed_lineage(tmp_path, obs=obs)
    gens = _gens(str(tmp_path))
    newest = os.path.join(str(tmp_path), gens[-1])
    _flip_bytes(os.path.join(newest, "offset.json"), offset=2)
    ckpt, offset = sup.latest_checkpoint()
    assert os.path.basename(ckpt) == gens[-2]  # fell back one generation
    assert offset == int(gens[-2].split("-")[1])
    snap = obs.snapshot()
    assert snap["ckpt_integrity_failures"] == 1
    assert snap["ckpt_lineage_fallbacks"] == 1
    kinds = [e["kind"] for e in obs.flight.snapshot()["events"]]
    assert "ckpt_corrupt" in kinds and "lineage_fallback" in kinds
    # the corrupt generation left a postmortem naming the evidence
    assert any(n.startswith("postmortem-")
               for n in os.listdir(str(tmp_path)))


def test_all_generations_corrupt_restores_none(tmp_path):
    sup, _ = committed_lineage(tmp_path)
    for g in _gens(str(tmp_path)):
        _flip_bytes(os.path.join(str(tmp_path), g, "offset.json"),
                    offset=2)
    assert sup.latest_checkpoint() is None


def test_stale_pointer_restores_newest_committed_generation(tmp_path):
    """A crash between the bundle rename (THE commit point) and the
    pointer flip leaves LATEST one generation stale. Restores must take
    the newest generation by POSITION: the stale pointer target's ledger
    predates emissions the newest bundle already closed, so restoring it
    re-delivers them to the consumer — exactly-once broken by the
    supervisor's own bookkeeping."""
    obs = Observability(flight=FlightRecorder(capacity=256))
    sup, out1 = committed_lineage(tmp_path, obs=obs)
    gens = _gens(str(tmp_path))
    # rewind the pointer one generation, as the crash would leave it
    with open(os.path.join(str(tmp_path), "LATEST.json"), "w") as f:
        json.dump({"dir": gens[-2]}, f)

    sup2 = Supervisor(str(tmp_path), clock=ManualClock(),
                      keep_checkpoints=3)
    ckpt, offset = sup2.latest_checkpoint()
    assert os.path.basename(ckpt) == gens[-1]   # newest, not the pointer
    assert offset == int(gens[-1].split("-")[1])

    # cross-process restart: a FRESH sink restored from the newest
    # ledger replays nothing — zero re-deliveries of pre-crash output
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(100)]
    out2 = run_supervised(records, make_conn_op, sup2, sink=sink,
                          checkpoint_every=25, final_watermark=10_000)
    assert out2 == []                            # all delivered pre-crash
    assert sink.suppressed == 0                  # nothing even replayed


def test_unverifiable_garbage_newer_than_pointer_distrusted(tmp_path):
    """The inverse guard: a ``ckpt-<pos>`` dir NEWER than the committed
    pointer but with no manifest cannot be a stale-pointer commit (a
    real commit seals its manifest before the rename) — it is foreign
    garbage and must not be restored."""
    sup, _ = committed_lineage(tmp_path)
    gens = _gens(str(tmp_path))
    torn = os.path.join(str(tmp_path), "ckpt-99999")
    os.makedirs(torn)
    with open(os.path.join(torn, "offset.json"), "w") as f:
        f.write("{not json")

    sup2 = Supervisor(str(tmp_path), clock=ManualClock(),
                      keep_checkpoints=3)
    ckpt, _ = sup2.latest_checkpoint()
    assert os.path.basename(ckpt) == gens[-1]    # garbage skipped


def test_supervised_run_recovers_through_corrupt_latest(tmp_path):
    """End-to-end acceptance: corrupt the newest checkpoint, crash the
    run, and the recovery restores the older verifying generation —
    delivered output still bit-matches the uninterrupted oracle."""
    from scotty_tpu.resilience.chaos import ChaosError

    oracle_dir = os.path.join(str(tmp_path), "oracle")
    sup = Supervisor(oracle_dir, clock=ManualClock())
    records = [(f"k{i % 3}", float(i), i * 10) for i in range(100)]
    oracle = run_supervised(records, make_conn_op, sup,
                            sink=TransactionalSink(mode=EXACTLY_ONCE),
                            checkpoint_every=25, final_watermark=10_000)

    crash_dir = os.path.join(str(tmp_path), "crashy")
    sup2 = Supervisor(crash_dir, clock=ManualClock(), max_restarts=4)
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    state = {"armed": True}

    class Source:
        def __len__(self):
            return len(records)

        def __getitem__(self, sl):
            def gen():
                base = sl.start or 0
                for i, r in enumerate(records[sl]):
                    if state["armed"] and base + i == 60:
                        state["armed"] = False
                        # corrupt the newest committed generation, then
                        # crash: recovery MUST verify, fall back, and
                        # replay further
                        gens = _gens(crash_dir)
                        _flip_bytes(os.path.join(
                            crash_dir, gens[-1], "ledger.json"), offset=2)
                        raise ChaosError("crash with corrupt latest")
                    yield r

            return gen()

    out = run_supervised(Source(), make_conn_op, sup2, sink=sink,
                         checkpoint_every=25, final_watermark=10_000)
    assert out == oracle
    assert sink.suppressed > 0               # the deeper replay happened


def test_stale_tmps_swept_on_construction_and_commit(tmp_path):
    d = str(tmp_path)
    os.makedirs(os.path.join(d, "ckpt-7.tmp"))
    with open(os.path.join(d, "LATEST.json.tmp"), "w") as f:
        f.write("{")
    Supervisor(d, clock=ManualClock())       # the startup sweep
    assert not [n for n in os.listdir(d) if ".tmp" in n]
    # ...and a tmp stranded mid-run is swept by the next commit
    sup = Supervisor(d, clock=ManualClock())
    os.makedirs(os.path.join(d, "ckpt-9.tmp"))
    sup.commit_checkpoint(
        1, lambda p: fsio.write_bytes(os.path.join(p, "x.json"), b"{}"),
        offset=1)
    assert not [n for n in os.listdir(d) if ".tmp" in n]


def test_torn_latest_pointer_recovers_from_names(tmp_path):
    sup, _ = committed_lineage(tmp_path)
    with open(os.path.join(str(tmp_path), "LATEST.json"), "w") as f:
        f.write('{"di')                      # torn pointer
    ckpt, offset = sup.latest_checkpoint()
    assert os.path.basename(ckpt) == _gens(str(tmp_path))[-1]


# -- fsck CLI ----------------------------------------------------------------

def test_fsck_clean_dir_exits_zero(tmp_path, capsys):
    from scotty_tpu.obs.fsck import fsck_main

    committed_lineage(tmp_path)
    assert fsck_main(str(tmp_path)) == 0
    out = capsys.readouterr().out
    assert "verdict: clean" in out
    assert "ledger epoch=" in out            # ledger heads surfaced


def test_fsck_flags_corruption_and_stale_tmp(tmp_path, capsys):
    from scotty_tpu.obs.fsck import fsck_main

    committed_lineage(tmp_path)
    gens = _gens(str(tmp_path))
    _flip_bytes(os.path.join(str(tmp_path), gens[-1], "offset.json"),
                offset=2)
    os.makedirs(os.path.join(str(tmp_path), "ckpt-99.tmp"))
    rc = fsck_main(str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 1                           # findings, but recoverable
    assert "CORRUPT" in out and "offset.json" in out
    assert "stale tmp: ckpt-99.tmp" in out
    assert f"restore would use: {gens[-2]}" in out


def test_fsck_pre_integrity_bundles_are_recoverable(tmp_path, capsys):
    """Pre-integrity bundles (no manifest) DO restore — the Supervisor
    accepts them unverified — so fsck must exit 1 (recoverable), not 2,
    and name the generation a restart would actually use."""
    from scotty_tpu.obs.fsck import fsck_main

    committed_lineage(tmp_path)
    gens = _gens(str(tmp_path))
    for g in gens:
        os.remove(os.path.join(str(tmp_path), g, "MANIFEST.json"))
    rc = fsck_main(str(tmp_path))
    out = capsys.readouterr().out
    assert rc == 1
    assert f"restore would use: {gens[-1]}" in out
    assert "restores UNVERIFIED" in out
    # and a supervised restart agrees
    sup = Supervisor(str(tmp_path), clock=ManualClock())
    ckpt, _ = sup.latest_checkpoint()
    assert os.path.basename(ckpt) == gens[-1]


def test_fsck_nothing_verifies_exits_two(tmp_path, capsys):
    from scotty_tpu.obs.fsck import fsck_main

    committed_lineage(tmp_path)
    for g in _gens(str(tmp_path)):
        shutil.rmtree(os.path.join(str(tmp_path), g))
    rc = fsck_main(str(tmp_path))
    assert rc == 2
    assert "no checkpoint generations found" in capsys.readouterr().out


def test_fsck_json_single_bundle(tmp_path, capsys):
    from scotty_tpu.obs.fsck import fsck_main

    d = sealed_bundle(tmp_path)
    assert fsck_main(d, as_json=True) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["ok"] is True
    assert doc["generations"][0]["ok"] is True


def test_fsck_cli_entrypoint(tmp_path):
    import subprocess
    import sys

    committed_lineage(tmp_path)
    r = subprocess.run(
        [sys.executable, "-m", "scotty_tpu.obs", "fsck", str(tmp_path)],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert r.returncode == 0, r.stderr
    assert "verdict: clean" in r.stdout
