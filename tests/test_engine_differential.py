"""Differential tests: TPU engine vs the reference-semantics simulator.

SURVEY.md §4's transferable strategy item (d): the host simulator (exact
reference behavior, validated by the transliterated reference suite) is the
oracle; the device engine must produce identical window results — same
triggered windows in the same order, same has_value flags, same aggregate
values — on scripted and randomized streams.
"""

import numpy as np
import pytest

from scotty_tpu import (
    CountAggregation,
    FixedBandWindow,
    MaxAggregation,
    MeanAggregation,
    MinAggregation,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator

Time = WindowMeasure.Time

SMALL = EngineConfig(capacity=1 << 12, batch_size=64, annex_capacity=256,
                     min_trigger_pad=32)


def run_both(windows, agg_factories, stream, watermarks, lateness=1000,
             config=SMALL, allow_ghosts=False):
    """Drive simulator + engine with the same scripted stream; compare
    results at every watermark.

    ``allow_ghosts`` (OOO count+time mixes): tolerate the reference's
    ghost-window artifact — see PARITY.md deviation 7. A ripple transiting
    records through an empty slice leaves invertible aggregate state at
    the combine identity with ``hasValue`` stuck true, so the reference
    emits spurious ``sum=0`` windows that contain no records; the engine
    emits ``has_value=False`` for them, consistent with its own (and the
    reference's own) in-order empty-window behavior.
    """
    sim = SlicingWindowOperator()
    eng = TpuWindowOperator(config=config)
    for op in (sim, eng):
        for w in windows:
            op.add_window_assigner(w)
        for mk in agg_factories:
            op.add_aggregation(mk())
        op.set_max_lateness(lateness)

    # `watermarks` is a list of (after_index, wm_ts): each watermark fires
    # after the stream tuple at that index has been processed.
    pos = 0
    for after_idx, wm in watermarks:
        while pos <= after_idx and pos < len(stream):
            v, ts = stream[pos]
            sim.process_element(v, ts)
            eng.process_element(v, ts)
            pos += 1
        r_sim = sim.process_watermark(wm)
        r_eng = eng.process_watermark(wm)
        compare(r_sim, r_eng, wm, allow_ghosts=allow_ghosts)
    return sim, eng


def _is_ghost(sim_w, eng_w) -> bool:
    """Reference ghost window: hasValue true but every aggregate value is
    an identity artifact of add-then-invert (0 or None); the engine
    reports it empty."""
    if eng_w.has_value() or not sim_w.has_value():
        return False
    return all(v is None or (isinstance(v, (int, float)) and v == 0)
               for v in sim_w.get_agg_values())


def compare(r_sim, r_eng, wm, allow_ghosts=False):
    assert len(r_sim) == len(r_eng), (
        f"@wm={wm}: simulator emitted {len(r_sim)} windows, engine "
        f"{len(r_eng)}:\n sim={r_sim}\n eng={r_eng}")
    for i, (a, b) in enumerate(zip(r_sim, r_eng)):
        assert a.get_start() == b.get_start(), (i, wm, a, b)
        assert a.get_end() == b.get_end(), (i, wm, a, b)
        if allow_ghosts and _is_ghost(a, b):
            continue
        assert a.has_value() == b.has_value(), (i, wm, a, b)
        if a.has_value():
            va, vb = a.get_agg_values(), b.get_agg_values()
            assert len(va) == len(vb), (i, wm, a, b)
            for x, y in zip(va, vb):
                assert float(x) == pytest.approx(float(y), rel=1e-5), (
                    i, wm, a, b)


def test_tumbling_sum_inorder():
    stream = [(1, 1), (2, 19), (3, 23), (4, 31), (5, 49), (6, 50)]
    run_both([TumblingWindow(Time, 10)], [SumAggregation], stream,
             [(2, 22), (5, 55)])


def test_tumbling_multiwindow_multiagg():
    stream = [(i % 7 + 1, i * 3) for i in range(40)]
    run_both(
        [TumblingWindow(Time, 10), TumblingWindow(Time, 25)],
        [SumAggregation, MinAggregation, MaxAggregation, CountAggregation,
         MeanAggregation],
        stream, [(9, 30), (19, 60), (39, 121)])


def test_sliding_sum():
    stream = [(1, 0), (2, 5), (3, 12), (4, 18), (5, 25), (6, 34), (7, 41)]
    run_both([SlidingWindow(Time, 10, 5)], [SumAggregation], stream,
             [(3, 20), (6, 40), (6, 50)])


def test_sliding_plus_tumbling():
    stream = [(i + 1, i * 4 + (i % 3)) for i in range(30)]
    run_both(
        [SlidingWindow(Time, 20, 5), TumblingWindow(Time, 15)],
        [SumAggregation, MaxAggregation],
        stream, [(9, 40), (19, 80), (29, 130)])


def test_fixed_band():
    stream = [(1, 2), (2, 5), (3, 11), (4, 18), (5, 22), (6, 30)]
    run_both([FixedBandWindow(Time, 5, 10)], [SumAggregation], stream,
             [(3, 16), (5, 31)])


def test_band_plus_sliding():
    stream = [(i + 1, i * 2) for i in range(25)]
    run_both(
        [FixedBandWindow(Time, 10, 20), SlidingWindow(Time, 10, 2)],
        [SumAggregation, MinAggregation],
        stream, [(12, 26), (24, 50)])


def test_empty_gaps_between_tuples():
    # tuples skip whole window ranges: empty windows must still be emitted
    # (has_value False) and slice gaps must not corrupt range queries.
    stream = [(1, 1), (2, 3), (3, 55), (4, 57), (5, 140)]
    run_both([TumblingWindow(Time, 10)], [SumAggregation, MeanAggregation],
             stream, [(1, 10), (3, 60), (4, 150)])


def test_out_of_order_within_lateness():
    # late tuples fold into existing slices (no session windows → no repair)
    stream = [(1, 10), (2, 20), (3, 31), (4, 15), (5, 42), (6, 8), (7, 51)]
    run_both([TumblingWindow(Time, 10)], [SumAggregation, MaxAggregation],
             stream, [(6, 55)], lateness=1000)


def test_out_of_order_into_empty_range_annex():
    # a late tuple lands in a grid range that was never materialized → annex
    stream = [(1, 5), (2, 60), (3, 25), (4, 61), (5, 35), (6, 70)]
    run_both([TumblingWindow(Time, 10)], [SumAggregation, CountAggregation],
             stream, [(5, 80)], lateness=1000)


def test_out_of_order_across_watermarks():
    stream = [(1, 5), (2, 30), (3, 12), (4, 45), (5, 33), (6, 95), (7, 58),
              (8, 99)]
    run_both([SlidingWindow(Time, 20, 10)], [SumAggregation],
             stream, [(2, 25), (4, 40), (7, 100)], lateness=1000)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_inorder(seed):
    rng = np.random.default_rng(seed)
    ts = np.cumsum(rng.integers(0, 7, size=300))
    vals = rng.integers(1, 100, size=300)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wm_points = sorted(rng.choice(np.arange(20, 280), size=5, replace=False))
    watermarks = [(int(p), int(ts[p]) + int(rng.integers(0, 5)))
                  for p in wm_points]
    # strictly increasing watermark ts
    watermarks = [(p, w) for j, (p, w) in enumerate(watermarks)
                  if all(w > w2 for _, w2 in watermarks[:j])]
    run_both(
        [TumblingWindow(Time, 13), SlidingWindow(Time, 40, 8),
         TumblingWindow(Time, 50)],
        [SumAggregation, MinAggregation, MaxAggregation, MeanAggregation],
        stream, watermarks)


@pytest.mark.parametrize("seed", [3, 4])
def test_randomized_out_of_order(seed):
    rng = np.random.default_rng(seed)
    base = np.cumsum(rng.integers(0, 6, size=300))
    jitter = rng.integers(0, 40, size=300)
    ts = np.maximum(base - jitter, 0)          # ~bounded disorder
    vals = rng.integers(1, 100, size=300)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wm_points = sorted(rng.choice(np.arange(50, 280), size=4, replace=False))
    watermarks = []
    for p in wm_points:
        w = int(np.max(ts[:p + 1])) + 1
        if not watermarks or w > watermarks[-1][1]:
            watermarks.append((int(p), w))
    run_both(
        [TumblingWindow(Time, 11), SlidingWindow(Time, 30, 10)],
        [SumAggregation, CountAggregation, MaxAggregation],
        stream, watermarks, lateness=10_000)


def test_batched_ingest_equals_scalar():
    # process_elements([...]) must equal element-at-a-time ingestion
    rng = np.random.default_rng(7)
    ts = np.cumsum(rng.integers(0, 5, size=200)).astype(np.int64)
    vals = rng.integers(1, 50, size=200).astype(np.float32)

    def mk():
        op = TpuWindowOperator(config=SMALL)
        op.add_window_assigner(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        return op

    a, b = mk(), mk()
    for v, t in zip(vals, ts):
        a.process_element(float(v), int(t))
    b.process_elements(vals, ts)
    wm = int(ts[-1]) + 1
    compare(a.process_watermark(wm), b.process_watermark(wm), wm)


# ---------------------------------------------------------------------------
# size % slide != 0: exact-semantics deviation
# ---------------------------------------------------------------------------
# The reference slices on the slide grid only and its t_last containment
# DROPS the straddling slice's in-window tuples when a window end falls off
# the grid (AggregateWindowState.java:25-31). The engine instead adds the
# window-end residue grids to the slice grid (EngineSpec.offset_periods) and
# returns EXACT window aggregates — so these specs are checked against a
# brute-force per-window oracle instead of the reference simulator.


def run_exact(windows, agg_factories, stream, watermarks, lateness=1000):
    eng = TpuWindowOperator(config=SMALL)
    for w in windows:
        eng.add_window_assigner(w)
    for mk in agg_factories:
        eng.add_aggregation(mk())
    eng.set_max_lateness(lateness)
    kinds = [type(mk()).__name__ for mk in agg_factories]

    pos = 0
    n_checked = 0
    for after_idx, wm in watermarks:
        while pos <= after_idx and pos < len(stream):
            v, ts = stream[pos]
            eng.process_element(v, ts)
            pos += 1
        seen_v = np.asarray([v for v, _ in stream[:pos]], dtype=np.float64)
        seen_t = np.asarray([t for _, t in stream[:pos]], dtype=np.int64)
        for w in eng.process_watermark(wm):
            m = (seen_t >= w.get_start()) & (seen_t < w.get_end())
            assert w.has_value() == bool(m.any()), (wm, w)
            if not w.has_value():
                continue
            n_checked += 1
            sel = seen_v[m]
            for kind, got in zip(kinds, w.get_agg_values()):
                exp = {"SumAggregation": sel.sum, "MinAggregation": sel.min,
                       "MaxAggregation": sel.max,
                       "CountAggregation": lambda: len(sel),
                       "MeanAggregation": sel.mean}[kind]()
                assert float(got) == pytest.approx(float(exp), rel=1e-5), (
                    wm, w, kind, exp)
    assert n_checked > 0


def test_sliding_size_not_multiple_of_slide_exact():
    stream = [(i % 9 + 1, i * 3 + (i % 2)) for i in range(60)]
    run_exact([SlidingWindow(Time, 25, 10)],
              [SumAggregation, MinAggregation, CountAggregation],
              stream, [(19, 66), (39, 131), (59, 200)])


def test_sliding_nondivisible_out_of_order():
    rng = np.random.default_rng(11)
    base = np.cumsum(rng.integers(0, 5, size=150))
    ts = np.maximum(base - rng.integers(0, 25, size=150), 0)
    vals = rng.integers(1, 50, size=150)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wms = []
    for p in (49, 99, 149):
        w = int(np.max(ts[:p + 1])) + 1
        if not wms or w > wms[-1][1]:
            wms.append((p, w))
    run_exact([SlidingWindow(Time, 25, 10)],
              [SumAggregation, MaxAggregation],
              stream, wms, lateness=10_000)


def test_mixed_nondivisible_grids_exact():
    stream = [(i % 5 + 1, i * 2 + (i % 3)) for i in range(80)]
    run_exact([SlidingWindow(Time, 25, 10), TumblingWindow(Time, 7),
               SlidingWindow(Time, 9, 4)],
              [SumAggregation, MeanAggregation],
              stream, [(39, 85), (79, 170)])


# ---------------------------------------------------------------------------
# count-measure device path
# ---------------------------------------------------------------------------


def test_count_tumbling_inorder():
    # reference scenario (TumblingWindowOperatorTest count cases, in-order)
    stream = [(1, 1), (1, 19), (1, 29), (2, 39), (2, 49), (2, 50), (1, 51)]
    run_both([TumblingWindow(WindowMeasure.Count, 3)], [SumAggregation],
             stream, [(6, 55)])


def test_count_two_windows_inorder():
    stream = [(1, 1), (1, 19), (1, 29), (2, 39), (1, 41), (2, 45), (2, 50),
              (1, 51), (3, 52)]
    run_both([TumblingWindow(WindowMeasure.Count, 3),
              TumblingWindow(WindowMeasure.Count, 5)],
             [SumAggregation], stream, [(8, 55)])


def test_count_mixed_with_time_inorder():
    stream = [(i + 1, i * 7) for i in range(30)]
    run_both([TumblingWindow(WindowMeasure.Count, 4),
              TumblingWindow(Time, 50)],
             [SumAggregation, MaxAggregation], stream,
             [(9, 65), (19, 135), (29, 205)])


def test_count_multi_watermark():
    stream = [(1, 1), (1, 19), (1, 29), (2, 39), (1, 41), (2, 44)]
    run_both([TumblingWindow(WindowMeasure.Count, 3)], [SumAggregation],
             stream, [(3, 40), (5, 55)])


def test_count_out_of_order_matches_oracle():
    """Round 3: count-measure OOO runs on device (record-buffer rank
    ranges — the closed form of the reference ripple,
    SliceManager.java:77-85). Late tuples across flushed batches must
    match the simulator."""
    stream = [(1, 3), (2, 20), (3, 5), (4, 30), (5, 8), (6, 40), (7, 41)]
    run_both([TumblingWindow(WindowMeasure.Count, 3)], [SumAggregation],
             stream, [(1, 25), (4, 35), (6, 45)], lateness=1000)


def test_count_time_mix_out_of_order_matches_oracle():
    """Round 4: OOO count+time mixes run on device (r3 raised here). The
    reference ripple (SliceManager.java:64-86) is realized as record-buffer
    rank ranges + the arrival-order host cut calculus; ALL window values
    come from record rank ranges once a late tuple was seen (mix_rec
    query, engine/core.py::build_query)."""
    stream = [(1, 3), (2, 20), (3, 5), (4, 30), (5, 8), (6, 40), (7, 41),
              (8, 33), (9, 55)]
    run_both([TumblingWindow(WindowMeasure.Count, 3),
              TumblingWindow(Time, 10)],
             [SumAggregation, MaxAggregation], stream,
             [(1, 25), (4, 35), (6, 45), (8, 60)], lateness=1000)


@pytest.mark.parametrize("seed", [3, 11, 29])
def test_count_time_mix_ooo_differential(seed):
    """Randomized OOO count+time mixed streams (distinct timestamps — the
    reference's TreeSet record retention drops equal-ts records, a
    documented quirk not worth reproducing) vs the simulator: the last
    workload class that was host-only in r3 (VERDICT r3 item 1).

    Window sizes are multiples of their slides so the engine's union grid
    equals the reference's window-start grid: for size-not-multiple-of-
    slide sliding windows the engine's exact offset-residue grid (the
    documented r1 deviation, EngineSpec.offset_periods) composes with the
    ripple's rank semantics into answers that differ from the reference's
    straddling-slice drops — see PARITY.md."""
    rng = np.random.default_rng(seed)
    n = 150
    base = np.sort(rng.choice(np.arange(1, 2500), size=n, replace=False))
    # unconstrained bounded shuffle: with a time grid the bootstrap slices
    # cover [0, first ts), so below-first late inserts are in contract
    # (unlike the count-only fuzz above, where they crash the reference)
    order = np.argsort(np.arange(n) + rng.uniform(0, 20, size=n),
                       kind="stable")
    ts = base[order]
    vals = rng.integers(1, 60, size=n)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wms = []
    for i, p in enumerate((n // 4, n // 2, 3 * n // 4, n - 1)):
        met = int(np.max(ts[:p + 1]))
        w = met - int(rng.integers(5, 40)) if i % 2 == 0 else met + 1
        if w > 0 and (not wms or w > wms[-1][1]):
            wms.append((p, w))
    run_both([TumblingWindow(WindowMeasure.Count, 7),
              TumblingWindow(Time, 40),
              SlidingWindow(Time, 50, 25)],
             [SumAggregation, MaxAggregation, MeanAggregation],
             stream, wms, lateness=10_000, allow_ghosts=True)


def test_count_time_mix_first_watermark_clamp():
    """A mixed stream starting well above 0: the reference's first-watermark
    clamp reads the FIRST-INSERTED slice, which with a count measure is the
    count bootstrap cut at the first arrival's ts (WindowManager.java:51-55,
    StreamSlicer.java:37-44) — no leading time windows below it (r4 review
    finding)."""
    stream = [(1, 74), (2, 136), (3, 90), (4, 150)]
    run_both([TumblingWindow(WindowMeasure.Count, 3),
              TumblingWindow(Time, 40)],
             [SumAggregation], stream, [(2, 140), (3, 160)],
             lateness=10_000, allow_ghosts=True)


@pytest.mark.parametrize("seed", [7, 21, 35])
def test_count_out_of_order_differential(seed):
    """Randomized count-only OOO streams (distinct timestamps — the
    reference's TreeSet record retention drops equal-ts records, a
    documented quirk not worth reproducing) vs the simulator."""
    rng = np.random.default_rng(seed)
    n = 160
    base = np.sort(rng.choice(np.arange(1, 3000), size=n, replace=False))
    # bounded local shuffle: distinct timestamps, arrival displaced ≤ ~25
    # positions; the first arrival stays the global minimum (below-first
    # inserts crash the reference — out of contract)
    order = np.argsort(np.arange(n) + rng.uniform(0, 25, size=n),
                       kind="stable")
    order = np.concatenate(([0], order[order != 0]))
    ts = base[order]
    vals = rng.integers(1, 60, size=n)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wms = []
    for i, p in enumerate((n // 4, n // 2, 3 * n // 4, n - 1)):
        # alternate watermarks INSIDE the disordered region (probing the
        # rippled-t_last step-back) and just past the max event time
        met = int(np.max(ts[:p + 1]))
        w = met - int(rng.integers(5, 40)) if i % 2 == 0 else met + 1
        if w > 0 and (not wms or w > wms[-1][1]):
            wms.append((p, w))
    run_both([TumblingWindow(WindowMeasure.Count, 7),
              TumblingWindow(WindowMeasure.Count, 3)],
             [SumAggregation, MaxAggregation, MeanAggregation],
             stream, wms, lateness=10_000)


# ---------------------------------------------------------------------------
# pure-session device path
# ---------------------------------------------------------------------------


def test_session_inorder():
    from scotty_tpu import SessionWindow

    stream = [(1, 0), (2, 3), (3, 20), (4, 22), (5, 60), (6, 61), (7, 63)]
    run_both([SessionWindow(Time, 10)], [SumAggregation], stream,
             [(3, 40), (6, 100)])


def test_session_inorder_multi_agg():
    from scotty_tpu import SessionWindow

    rng = np.random.default_rng(9)
    ts, t = [], 0
    for i in range(120):
        t += int(rng.integers(0, 4)) if i % 20 else 50   # periodic gaps
        ts.append(t)
    vals = rng.integers(1, 30, size=120)
    stream = [(int(v), int(tt)) for v, tt in zip(vals, ts)]
    run_both([SessionWindow(Time, 12)],
             [SumAggregation, MinAggregation, MaxAggregation, MeanAggregation],
             stream, [(59, ts[59] + 1), (119, ts[119] + 100)])


def test_session_still_open_not_emitted():
    from scotty_tpu import SessionWindow

    stream = [(1, 0), (2, 5), (3, 8)]
    # watermark inside gap: session [0, 8+10) not complete at wm 10
    sim, eng = run_both([SessionWindow(Time, 10)], [SumAggregation], stream,
                        [(2, 10)])
    # completes later
    r_sim = sim.process_watermark(30)
    r_eng = eng.process_watermark(30)
    compare(r_sim, r_eng, 30)


# ---------------------------------------------------------------------------
# Dynamic window addition on the device path
# (TumblingWindowOperatorTest.java:96-145 semantics; VERDICT r1 item 7)
# ---------------------------------------------------------------------------


def run_both_dynamic(initial_windows, added, agg_factories, stream,
                     watermarks, lateness=1000, config=SMALL):
    """Like run_both, but registers `added` windows mid-stream: ``added`` is
    a list of (after_index, window) — each window is registered right after
    the stream tuple at that index.

    Oracle caveat: the simulator reproduces the reference's cached-edge
    behavior (the current slice keeps absorbing tuples until the STALE
    pre-addition edge after a dynamic addition); the engine re-grids
    immediately — a documented deviation (TpuWindowOperator.
    _add_window_dynamic). Differential cases must therefore place additions
    where the old and new grids share the next edge (e.g. right after a
    tuple that just crossed an old-grid edge); arbitrary addition points
    diverge inside [addition_ts, stale_edge) by design."""
    sim = SlicingWindowOperator()
    eng = TpuWindowOperator(config=config)
    for op in (sim, eng):
        for w in initial_windows:
            op.add_window_assigner(w)
        for mk in agg_factories:
            op.add_aggregation(mk())
        op.set_max_lateness(lateness)

    add_at = dict()
    for idx, w in added:
        add_at.setdefault(idx, []).append(w)
    pos = 0
    for after_idx, wm in watermarks:
        while pos <= after_idx and pos < len(stream):
            v, ts = stream[pos]
            sim.process_element(v, ts)
            eng.process_element(v, ts)
            for w in add_at.get(pos, ()):
                sim.add_window_assigner(w)
                eng.add_window_assigner(w)
            pos += 1
        compare(sim.process_watermark(wm), eng.process_watermark(wm), wm)
    return sim, eng


def test_dynamic_addition_finer_grid():
    # coarse Tumbling(20) first; add Tumbling(5) mid-stream: pre-addition
    # slices stay coarse, new windows straddling them must match the
    # reference's t_last containment (AggregateWindowState.java:25-31)
    stream = [(1, 1), (2, 19), (3, 29), (4, 34), (5, 49), (6, 61)]
    run_both_dynamic([TumblingWindow(Time, 20)],
                     [(1, TumblingWindow(Time, 5))],
                     [SumAggregation], stream, [(1, 22), (5, 70)])


def test_dynamic_addition_window_inside_coarse_slice():
    # one giant pre-addition slice fully spans several new small windows:
    # the engine's range query must return empty for them (hi<lo clamp),
    # exactly like the reference's containment check excludes the slice
    stream = [(1, 5), (2, 95), (3, 105), (4, 215), (5, 305)]
    run_both_dynamic([TumblingWindow(Time, 100)],
                     [(2, TumblingWindow(Time, 10))],
                     [SumAggregation, CountAggregation], stream,
                     [(2, 150), (4, 400)])


def test_dynamic_addition_sliding():
    # dynamically added overlapping sliding window over a random stream
    # (size % slide == 0, so the simulator is an exact oracle; non-divisible
    # sizes deviate deliberately — EngineSpec.offset_periods)
    rng = np.random.default_rng(3)
    ts = np.sort(rng.integers(0, 400, size=80))
    stream = [(int(v), int(t))
              for v, t in zip(rng.integers(1, 9, size=80), ts)]
    run_both_dynamic([TumblingWindow(Time, 50)],
                     [(20, SlidingWindow(Time, 30, 10))],
                     [SumAggregation, MaxAggregation], stream,
                     [(20, int(ts[20]) + 1), (79, int(ts[79]) + 500)])


def test_dynamic_addition_sliding_nondivisible_exact():
    # dynamically added Sliding(25,10) brings an offset residue grid with it:
    # POST-addition windows are exact (brute force oracle); the window ends
    # land on slice edges so no straddling-slice data is dropped.
    rng = np.random.default_rng(7)
    ts = np.sort(rng.integers(0, 400, size=60))
    vals = rng.integers(1, 9, size=60)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    add_idx = 19
    add_ts = int(ts[add_idx])

    eng = TpuWindowOperator(config=SMALL)
    eng.add_window_assigner(TumblingWindow(Time, 50))
    eng.add_aggregation(SumAggregation())
    for i, (v, t) in enumerate(stream):
        eng.process_element(v, t)
        if i == add_idx:
            eng.add_window_assigner(SlidingWindow(Time, 25, 10))
    wm = int(ts[-1]) + 500
    results = eng.process_watermark(wm)
    arr_t = np.asarray(ts, np.int64)
    arr_v = np.asarray(vals, np.float64)
    for w in results:
        s, e = w.get_start(), w.get_end()
        if e - s != 25 or s < add_ts:
            continue          # only post-addition sliding windows are exact
        m = (arr_t >= s) & (arr_t < e)
        expected = float(arr_v[m].sum())
        got = float(w.get_agg_values()[0]) if w.has_value() else 0.0
        assert got == pytest.approx(expected), (s, e)


@pytest.mark.parametrize("seed", [2, 5, 8, 14])
def test_randomized_specs_with_valid_watermarks(seed):
    """Randomized window mixes (pow2 tumbling, bands, divisible sliding) +
    bounded disorder, with watermark sequences that never run ahead of the
    observed max event time (the contract every real watermark policy
    satisfies; the reference crashes identically on tuples older than its
    oldest slice, so racing watermarks are out of contract)."""
    rng = np.random.default_rng(seed)
    pool = [
        lambda r: TumblingWindow(Time, int(r.choice([2, 8, 10, 25, 64]))),
        lambda r: SlidingWindow(Time, int(r.choice([20, 40, 80])),
                                int(r.choice([2, 4, 5, 10, 20]))),
        lambda r: FixedBandWindow(Time, int(r.integers(0, 200)),
                                  int(r.integers(10, 100))),
    ]
    wins = []
    for _ in range(int(rng.integers(1, 4))):
        w = pool[int(rng.integers(0, len(pool)))](rng)
        if isinstance(w, SlidingWindow) and w.size % w.slide:
            continue
        wins.append(w)
    if not wins:
        wins = [TumblingWindow(Time, 10)]
    n = 200
    ts = np.sort(rng.integers(0, 1200, size=n))
    lateness = int(rng.choice([0, 50, 1000]))
    if lateness:
        late = rng.random(n) < 0.15
        ts = np.where(late, np.maximum(
            ts - rng.integers(0, lateness, size=n), 0), ts)
    stream = [(int(v), int(t))
              for v, t in zip(rng.integers(1, 99, size=n), ts)]
    wms = []
    for c in (n // 3, 2 * n // 3, n - 1):
        met = int(np.max(ts[:c + 1]))
        wms.append((c, max(1, met - int(rng.integers(0, 20)))))
    wms.append((n - 1, int(np.max(ts)) + 3000))
    run_both(wins, [SumAggregation, MinAggregation, CountAggregation],
             stream, wms, lateness=lateness or 1000)


def test_device_resident_ooo_batches_match_oracle():
    """ingest_device_batch accepts ts-sorted batches containing late tuples
    (the device-generated OOO benchmark path); results must match the
    simulator fed the same tuples."""
    import jax
    import jax.numpy as jnp

    rng = np.random.default_rng(5)
    B = 64
    cfg = EngineConfig(capacity=1 << 12, batch_size=B, annex_capacity=256,
                       min_trigger_pad=32)
    eng = TpuWindowOperator(config=cfg)
    sim = SlicingWindowOperator()
    for op in (eng, sim):
        op.add_window_assigner(TumblingWindow(Time, 10))
        op.add_window_assigner(SlidingWindow(Time, 40, 20))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(1000)

    lo = 0
    for i in range(6):
        base = np.sort(rng.integers(lo, lo + 100, size=B)).astype(np.int64)
        late = rng.random(B) < 0.2
        ts = np.sort(np.where(late, np.maximum(
            base - rng.integers(0, 80, size=B), 0), base)).astype(np.int64)
        vals = rng.integers(1, 9, size=B).astype(np.float32)
        eng.ingest_device_batch(jax.device_put(jnp.asarray(vals)),
                                jax.device_put(jnp.asarray(ts)),
                                int(ts.min()), int(ts.max()))
        sim.process_elements(vals, ts)
        lo += 100
        if i % 2 == 1:
            compare(sim.process_watermark(lo), eng.process_watermark(lo), lo)
    compare(sim.process_watermark(lo + 500),
            eng.process_watermark(lo + 500), lo + 500)


# ---------------------------------------------------------------------------
# Brute-force exactness fuzz: the engine's documented claim is EXACT window
# aggregates (it deviates from the reference only where the reference drops
# data — PARITY.md deviations). Verify against direct recomputation from
# the raw stream, which has no oracle quirks at all.
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", [11, 23, 37])
def test_engine_exact_vs_brute_force(seed):
    rng = np.random.default_rng(seed)
    wins = [TumblingWindow(Time, int(rng.choice([7, 16, 25, 60]))),
            SlidingWindow(Time, int(rng.choice([30, 45])),
                          int(rng.choice([5, 10, 15])))]
    n = 300
    ts = np.sort(rng.integers(0, 2000, size=n))
    lateness = 400
    late = rng.random(n) < 0.2
    ts = np.where(late, np.maximum(ts - rng.integers(0, lateness, size=n),
                                   0), ts)
    vals = rng.integers(1, 50, size=n).astype(np.int64)

    eng = TpuWindowOperator(config=SMALL)
    for w in wins:
        eng.add_window_assigner(w)
    eng.add_aggregation(SumAggregation())
    eng.add_aggregation(CountAggregation())
    eng.add_aggregation(MinAggregation())   # sparse-table query path
    eng.add_aggregation(MaxAggregation())
    eng.set_max_lateness(10_000)       # no GC interference with brute force

    arr_t = np.asarray(ts, np.int64)
    arr_v = np.asarray(vals, np.float64)
    pos = 0
    for cut, wm_off in ((n // 3, 5), (2 * n // 3, 11), (n - 1, 3000)):
        while pos <= cut:
            eng.process_element(int(vals[pos]), int(ts[pos]))
            pos += 1
        wm = int(np.max(arr_t[:cut + 1])) + wm_off
        seen = arr_t[:pos]
        seen_v = arr_v[:pos]
        for w in eng.process_watermark(wm):
            m = (seen >= w.get_start()) & (seen < w.get_end())
            want_sum = float(seen_v[m].sum())
            want_cnt = float(m.sum())
            if w.has_value():
                got_sum, got_cnt, got_min, got_max = (
                    float(x) for x in w.get_agg_values())
            else:
                got_sum = got_cnt = 0.0
                got_min = got_max = None
            assert got_cnt == want_cnt, (w, want_cnt)
            assert got_sum == pytest.approx(want_sum, rel=1e-5), (w, want_sum)
            if want_cnt:
                assert got_min == float(seen_v[m].min()), w
                assert got_max == float(seen_v[m].max()), w


def test_multi_gap_pure_sessions():
    """Two concurrent session windows with different gaps
    (SessionWindowOperatorTest.java:207-236, in-order): the device runs one
    session state per gap; results match the simulator. Watermarks fire
    inside long stream gaps so the reference's re-opened-session quirk
    (PARITY.md deviation 5) can't trigger."""
    from scotty_tpu import SessionWindow

    rng = np.random.default_rng(21)
    t, stream, safe_points = 0, [], []
    for burst in range(12):
        for _ in range(int(rng.integers(5, 15))):
            t += int(rng.integers(0, 3))
            stream.append((int(rng.integers(1, 30)), t))
        safe_points.append((len(stream) - 1, t + 40))   # mid-long-gap
        t += int(rng.integers(60, 100))                 # >> both gaps
    wms = safe_points[3::4] + [safe_points[-1]]
    run_both([SessionWindow(Time, 8), SessionWindow(Time, 20)],
             [SumAggregation, MaxAggregation], stream, wms)


def test_count_survives_positive_gc_bound():
    """Count slices must keep real ts starts so the GC bound cannot drop
    records of pending count windows (review finding r3: grid_start==0
    polluted every start, and wall-clock-scale timestamps with a small
    lateness then GC'd live ranks)."""
    base = 100_000
    stream = [(i + 1, base + i * 7) for i in range(12)]
    stream += [(i + 1, base + 90 + i * 7) for i in range(8)]
    run_both([TumblingWindow(WindowMeasure.Count, 7),
              TumblingWindow(WindowMeasure.Count, 3)],
             [SumAggregation], stream,
             [(11, base + 80), (19, base + 200)], lateness=50)


def test_count_dynamic_time_addition_keeps_record_query():
    """Dynamic time-window addition on a count workload must rebuild the
    record-aware query kernel (review finding r3: the rebuild dropped the
    record_capacity argument and the next watermark raised TypeError)."""
    eng = TpuWindowOperator(config=SMALL)
    eng.add_window_assigner(TumblingWindow(WindowMeasure.Count, 3))
    eng.add_aggregation(SumAggregation())
    eng.process_elements([1, 2, 3, 4], [10, 20, 30, 40])
    assert [float(w.get_agg_values()[0])
            for w in eng.process_watermark(45) if w.has_value()] == [6.0]
    eng.add_window_assigner(TumblingWindow(Time, 50))
    eng.process_elements([5, 6], [60, 70])
    res = eng.process_watermark(120)
    vals = {(w.get_start(), w.get_end()): float(w.get_agg_values()[0])
            for w in res if w.has_value()}
    assert vals[(3, 6)] == 15.0            # count window [3,6): 4+5+6
    assert vals[(50, 100)] == 11.0         # added time window: 5+6


def test_count_minmax_full_record_buffer():
    """A count window spanning the ENTIRE record buffer (length == RC, a
    power of two) must still answer min/max — the log sweep needs the
    log2(N) level (review finding r3)."""
    cfg = EngineConfig(capacity=1 << 12, batch_size=64, annex_capacity=256,
                       min_trigger_pad=32, record_capacity=16)
    eng = TpuWindowOperator(config=cfg)
    eng.add_window_assigner(TumblingWindow(WindowMeasure.Count, 16))
    eng.add_aggregation(MinAggregation())
    eng.add_aggregation(MaxAggregation())
    vals = [float(v) for v in range(3, 19)]
    eng.process_elements(vals, [10 * i for i in range(16)])
    res = [w for w in eng.process_watermark(1000) if w.has_value()]
    assert len(res) == 1
    lo, hi = (float(x) for x in res[0].get_agg_values())
    assert (lo, hi) == (3.0, 18.0)


def _bursty_session_stream(rng, n_bursts, burst_span=100, jitter=300,
                           silence=1000):
    """Bursts of tuples separated by long silent gaps, with bounded late
    jitter. The silence (≥ ``silence`` − ``burst_span``) exceeds the jitter
    bound plus every session gap in use, so a late tuple can never reach
    back into a session emitted at a mid-gap watermark — keeping the
    documented re-opened-session deviation (PARITY.md #5) untriggerable
    while exercising every in-burst repair case (extend/merge/insert and the
    exact-gap arrival-order quirks, which the engine's sequential late scan
    reproduces bit-for-bit)."""
    stream, safe_wms = [], []
    for b in range(n_bursts):
        base = b * silence
        k = int(rng.integers(8, 20))
        ts = base + rng.integers(0, burst_span, size=k)
        late = rng.random(k) < 0.4
        ts = np.where(late, np.maximum(ts - rng.integers(0, jitter, size=k),
                                       base), ts)
        order = rng.permutation(k)          # arrival order ≠ ts order
        if b == 0:
            # a tuple below the FIRST tuple ever seen has no covering slice
            # and crashes the reference (ArrayList.get(-1) — out of
            # contract); arrive the global minimum first
            mn = int(np.argmin(ts))
            order = np.concatenate(([mn], order[order != mn]))
        for i in order:
            stream.append((int(rng.integers(1, 30)), int(ts[i])))
        safe_wms.append((len(stream) - 1, base + burst_span + jitter + 100))
    return stream, safe_wms


@pytest.mark.parametrize("seed", [1, 6, 13, 29])
def test_session_out_of_order_differential(seed):
    """OOO session repair on device (VERDICT r2 item 2): random bursty
    streams with ~40% late tuples in scrambled arrival order must match the
    host oracle exactly — including extend-start/extend-end/merge/insert
    and the exact-gap drop quirk (SessionWindow.java:40-98)."""
    from scotty_tpu import SessionWindow

    rng = np.random.default_rng(seed)
    stream, wms = _bursty_session_stream(rng, n_bursts=8)
    run_both([SessionWindow(Time, int(rng.choice([10, 25, 50])))],
             [SumAggregation, CountAggregation, MaxAggregation],
             stream, wms[1::2] + [wms[-1]], lateness=10_000)


def run_bounds_vs_sim_values_vs_brute(windows, agg_factories, stream,
                                      watermarks, lateness=10_000):
    """Differential harness for workloads where the REFERENCE drops data:
    with several window contexts over one slice store, a session window of
    context A can misalign with slices shaped by context B, and the
    reference's containment then emits the session with empty or partial
    values (the same mechanism as PARITY.md deviation 5). Window boundaries
    and emission order still compare strictly against the simulator; values
    compare against brute-force recomputation over ``[start, end)`` — exact
    for grid windows by construction, and exact for session windows because
    a session's window span contains precisely its own tuples (live sessions
    are separated by > gap, and quirk-dropped tuples fall outside every
    emitted span)."""
    sim = SlicingWindowOperator()
    eng = TpuWindowOperator(config=SMALL)
    for op in (sim, eng):
        for w in windows:
            op.add_window_assigner(w)
        for mk in agg_factories:
            op.add_aggregation(mk())
        op.set_max_lateness(lateness)
    kinds = [type(mk()).__name__ for mk in agg_factories]

    pos = 0
    n_checked = 0
    for after_idx, wm in watermarks:
        while pos <= after_idx and pos < len(stream):
            v, ts = stream[pos]
            sim.process_element(v, ts)
            eng.process_element(v, ts)
            pos += 1
        r_sim = sim.process_watermark(wm)
        r_eng = eng.process_watermark(wm)
        assert len(r_sim) == len(r_eng), (wm, r_sim, r_eng)
        seen_t = np.asarray([t for _, t in stream[:pos]], np.int64)
        seen_v = np.asarray([v for v, _ in stream[:pos]], np.float64)
        for i, (a, b) in enumerate(zip(r_sim, r_eng)):
            assert a.get_start() == b.get_start(), (i, wm, a, b)
            assert a.get_end() == b.get_end(), (i, wm, a, b)
            m = (seen_t >= b.get_start()) & (seen_t < b.get_end())
            assert b.has_value() == bool(m.any()), (i, wm, b)
            if not b.has_value():
                continue
            n_checked += 1
            sel = seen_v[m]
            for kind, got in zip(kinds, b.get_agg_values()):
                exp = {"SumAggregation": sel.sum, "MinAggregation": sel.min,
                       "MaxAggregation": sel.max,
                       "CountAggregation": lambda: len(sel),
                       "MeanAggregation": sel.mean}[kind]()
                assert float(got) == pytest.approx(float(exp), rel=1e-5), (
                    i, wm, b, kind, exp)
    assert n_checked > 0


@pytest.mark.parametrize("seed", [4, 17])
def test_session_mixed_with_grid_out_of_order_differential(seed):
    """Sessions mixed with time-grid windows, out-of-order, on device
    (VERDICT r2 item 2b): grid windows answer from the slice buffer,
    sessions from their active-session arrays; boundaries/order match the
    simulator, values are exact (brute force)."""
    from scotty_tpu import SessionWindow

    rng = np.random.default_rng(seed)
    stream, wms = _bursty_session_stream(rng, n_bursts=6)
    run_bounds_vs_sim_values_vs_brute(
        [TumblingWindow(Time, 50), SessionWindow(Time, 20),
         SlidingWindow(Time, 200, 100)],
        [SumAggregation, MinAggregation],
        stream, wms[::2] + [wms[-1]])


def test_session_multi_gap_out_of_order_differential():
    """Two session windows with different gaps over one OOO stream: each
    device active-session array repairs independently
    (SessionWindowOperatorTest.java:207-236 generalized to disorder)."""
    from scotty_tpu import SessionWindow

    rng = np.random.default_rng(42)
    stream, wms = _bursty_session_stream(rng, n_bursts=8)
    run_bounds_vs_sim_values_vs_brute(
        [SessionWindow(Time, 8), SessionWindow(Time, 30)],
        [SumAggregation, MeanAggregation], stream,
        wms[2::3] + [wms[-1]])


def test_session_orphan_survives_watermarks_while_session_live():
    """An exact-gap orphan covered by a still-live session must survive
    watermark GC until that session emits (review finding r3): gap=10,
    orphan at 60 == B.first - gap, A/B later merge over it, in-order
    traffic keeps the merged session live across several watermarks."""
    from scotty_tpu import SessionWindow

    eng = TpuWindowOperator(config=SMALL)
    eng.add_window_assigner(SessionWindow(Time, 10))
    eng.add_aggregation(SumAggregation())
    eng.add_aggregation(CountAggregation())
    eng.set_max_lateness(20)

    feed = [(49, 1.0), (70, 2.0),      # sessions A=[49,49], B=[70,70]
            (60, 16.0),                # exact-gap orphan (60 == 70-10)
            (59, 4.0),                 # extends A to [49,59]
            (65, 8.0)]                 # merges A+B -> [49,70] (covers 60)
    for t, v in feed:
        eng.process_element(v, t)
    total, count = 31.0, 5
    t = 70
    for wm in (100, 130, 160):         # keep the session alive across GCs
        while t + 10 < wm + 25:
            t += 9
            eng.process_element(1.0, t)
            total += 1.0
            count += 1
        assert eng.process_watermark(wm) == []   # still open: nothing emits
    res = [w for w in eng.process_watermark(t + 1000) if w.has_value()]
    assert len(res) == 1
    got_sum, got_cnt = (float(x) for x in res[0].get_agg_values())
    assert got_cnt == count                     # orphan tuple counted
    assert got_sum == pytest.approx(total)      # orphan value recovered


def test_ingest_device_batch_honors_n_valid():
    """Pad lanes beyond n_valid must not aggregate (review finding: the
    mask was previously always all-true)."""
    import jax
    import jax.numpy as jnp

    op = TpuWindowOperator(config=SMALL)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    B = SMALL.batch_size
    ts = np.arange(B, dtype=np.int64) // 8          # ts 0..7
    ts[10:] = ts[9]                                 # pad lanes repeat
    vals = np.full((B,), 5.0, np.float32)
    op.ingest_device_batch(jax.device_put(jnp.asarray(vals)),
                           jax.device_put(jnp.asarray(ts)),
                           0, int(ts[9]), n_valid=10)
    res = [w for w in op.process_watermark(20) if w.has_value()]
    assert len(res) == 1
    assert float(res[0].get_agg_values()[0]) == 50.0    # 10 lanes, not B
