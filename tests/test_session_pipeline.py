"""Differential tests: the fused SessionStreamPipeline vs the host oracle.

The session pipeline is the benchmark execution mode for session workloads
(BASELINE config 5): silence-separated sessions at constant rate, one fused
dispatch per watermark interval. These tests materialize the pipeline's own
generated stream (bit-exact device RNG replay, silent intervals empty),
feed it to the reference-semantics simulator, and require identical window
results at every watermark — sessions, multi-gap sessions, and
session+sliding mixes.
"""

import numpy as np
import pytest

from scotty_tpu import (
    MaxAggregation,
    SessionWindow,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

Time = WindowMeasure.Time

CFG = EngineConfig(capacity=1 << 12, annex_capacity=8, min_trigger_pad=32)
SC = {"count": 6, "minGapMs": 1500, "maxGapMs": 4000}


def run_diff(windows, agg_factories, n_intervals=20, throughput=4000,
             seed=7):
    p = SessionStreamPipeline(
        windows, [mk() for mk in agg_factories], config=CFG,
        throughput=throughput, wm_period_ms=1000, max_lateness=1000,
        seed=seed, session_config=SC)
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    for mk in agg_factories:
        sim.add_aggregation(mk())
    sim.set_max_lateness(1000)

    p.reset()
    n_emitted = 0
    for i in range(n_intervals):
        out = p.run(1)[0]
        vals, ts = p.materialize_interval(i)
        if ts.size:
            order = np.argsort(ts, kind="stable")
            sim.process_elements(vals[order], ts[order])
        wm = (i + 1) * 1000
        want = {}
        for w in sim.process_watermark(wm):
            if w.has_value():
                want.setdefault((w.get_start(), w.get_end()),
                                w.get_agg_values())
        got = {(s, e): v for (s, e, c, v) in p.lowered_results(out)}
        assert set(got) == set(want), (i, set(want) ^ set(got))
        for k in want:
            for a, b in zip(want[k], got[k]):
                assert float(a) == pytest.approx(float(b), rel=2e-4), (i, k)
        n_emitted += len(got)
    p.check_overflow()
    return n_emitted


def test_session_pipeline_pure_session():
    n = run_diff([SessionWindow(Time, 1000)],
                 [SumAggregation, MaxAggregation])
    assert n > 0          # at least one session completed in the horizon


def test_session_pipeline_two_gaps():
    n = run_diff([SessionWindow(Time, 800), SessionWindow(Time, 2500)],
                 [SumAggregation])
    assert n > 0


def test_session_pipeline_session_sliding_mix():
    n = run_diff([SessionWindow(Time, 1000), SlidingWindow(Time, 5000, 500)],
                 [SumAggregation, MaxAggregation])
    assert n > 0


def test_session_pipeline_hll_matches_device_operator():
    """HLL oracle is the DEVICE operator path (same device lift/hash — the
    host HLL hashes differently by design, so host estimates are not
    comparable): identical tuples through TpuWindowOperator's session
    kernels must yield the same windows and the same register estimates
    as the pipeline's shared interval fold."""
    from scotty_tpu.core.aggregates import HyperLogLogAggregation
    from scotty_tpu.engine import TpuWindowOperator

    p = SessionStreamPipeline(
        [SessionWindow(Time, 1000)], [HyperLogLogAggregation(8)], config=CFG,
        throughput=4000, wm_period_ms=1000, max_lateness=1000, seed=7,
        session_config=SC)
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, batch_size=256, annex_capacity=8,
        min_trigger_pad=32))
    op.add_window_assigner(SessionWindow(Time, 1000))
    op.add_aggregation(HyperLogLogAggregation(8))
    op.set_max_lateness(1000)
    p.reset()
    total = 0
    for i in range(20):
        out = p.run(1)[0]
        vals, ts = p.materialize_interval(i)
        if ts.size:
            order = np.argsort(ts, kind="stable")
            op.process_elements(vals[order], ts[order])
        want = [((w.get_start(), w.get_end()), w.get_agg_values()[0])
                for w in op.process_watermark((i + 1) * 1000)
                if w.has_value()]
        got = [((s, e), v[0]) for (s, e, c, v) in p.lowered_results(out)]
        assert [k for k, _ in want] == [k for k, _ in got], i
        for (_, a), (_, b) in zip(want, got):
            # same tuples, same device lift → same registers → same estimate
            assert float(a) == pytest.approx(float(b), rel=1e-5), i
        total += len(got)
    p.check_overflow()
    assert total > 0


def test_session_steps_clean_under_transfer_guard():
    """ISSUE 9 satellite: warmed session steps (the donated three-carry
    step plus its per-interval (index, live) scalars) dispatch with
    zero implicit transfers under jax.transfer_guard("disallow");
    results still bit-match the host oracle."""
    import jax

    windows = [SessionWindow(Time, 1000)]
    p = SessionStreamPipeline(
        windows, [SumAggregation()], config=CFG, throughput=4000,
        wm_period_ms=1000, max_lateness=1000, seed=7, session_config=SC)
    sim = SlicingWindowOperator()
    for w in windows:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(1000)
    p.reset()
    outs = list(p.run(1))       # warmup: compile outside the guard
    with jax.transfer_guard("disallow"):
        outs.extend(p.run(5))
    p.sync()
    for i, out in enumerate(outs):
        vals, ts = p.materialize_interval(i)
        if ts.size:
            order = np.argsort(ts, kind="stable")
            sim.process_elements(vals[order], ts[order])
        want = {(w.get_start(), w.get_end()): w.get_agg_values()
                for w in sim.process_watermark((i + 1) * 1000)
                if w.has_value()}
        got = {(s, e): v for (s, e, c, v) in p.lowered_results(out)}
        assert set(got) == set(want), (i, set(want) ^ set(got))
        for k in want:
            for a, b in zip(want[k], got[k]):
                assert float(a) == pytest.approx(float(b), rel=2e-4), \
                    (i, k)
    p.check_overflow()
