"""Differential tests for the in-jit device telemetry (ISSUE 2 tentpole).

The DeviceMetrics pytree rides the carried state of every fused pipeline;
its counters must EXACTLY match host oracle replays of the same streams:

* late counts + age strata: a numpy arrival-order replay of the
  pipeline's ``materialize_interval*`` faces (running-max calculus,
  bucketed through the shared ``host_late_age_hist`` edges);
* triggers fired / non-empty windows: the reference-semantics
  ``simulator/`` operator fed the SAME materialized stream with the same
  watermark cadence (the count pipeline's OOO case uses the device
  operator instead — the simulator's TreeSet record dedup at equal ts is
  a reproduced reference artifact the pipelines deliberately don't share,
  tests/test_count_pipeline.py).

Also covered here: the ``obs diff`` regression gate (exit 0 on self-diff,
nonzero on an injected 10% throughput regression — tier-1, ISSUE 2
satellite) and the pinned legacy-generator anchor cell.
"""

import json

import numpy as np
import pytest

import jax

from scotty_tpu import (
    HyperLogLogAggregation,
    SessionWindow,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.obs import device as dev

Time, Count = WindowMeasure.Time, WindowMeasure.Count
CFG = EngineConfig(capacity=1 << 12, annex_capacity=256, min_trigger_pad=32)


# ---------------------------------------------------------------------------
# Oracles
# ---------------------------------------------------------------------------


def replay_lateness(p, n_iv, with_late_face=True):
    """Numpy arrival-order replay: (n_tuples, n_late, age_hist) over the
    pipeline's materialized stream — the host mirror of the in-jit
    running-max calculus."""
    met = np.iinfo(np.int64).min
    n_tup = n_late = 0
    ages = []
    for i in range(n_iv):
        parts = []
        if with_late_face and hasattr(p, "materialize_interval_late"):
            parts.append(p.materialize_interval_late(i)[1])
        parts.append(p.materialize_interval(i)[1])
        for ts in parts:
            for t in ts:
                n_tup += 1
                if t < met:
                    n_late += 1
                    ages.append(met - t)
                met = max(met, int(t))
    return n_tup, n_late, dev.host_late_age_hist(ages)


def oracle_trigger_counts(make_op, p, n_iv, with_late_face=True):
    """(triggers, nonempty) totals from an operator oracle fed the same
    materialized arrival stream, one watermark per interval."""
    op = make_op()
    triggers = nonempty = 0
    for i in range(n_iv):
        if with_late_face and hasattr(p, "materialize_interval_late"):
            lv, lts = p.materialize_interval_late(i)
            if len(lv):
                op.process_elements(lv, lts)
        vs, ts = p.materialize_interval(i)
        op.process_elements(vs, ts)
        res = op.process_watermark((i + 1) * p.wm_period_ms)
        triggers += len(res)
        nonempty += sum(1 for w in res if w.has_value())
    return triggers, nonempty


def make_sim(windows, agg, lateness):
    def build():
        op = SlicingWindowOperator()
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(agg)
        op.set_max_lateness(lateness)
        return op
    return build


def make_dev_op(windows, agg, lateness, record_capacity=0):
    def build():
        op = TpuWindowOperator(config=EngineConfig(
            capacity=1 << 12, batch_size=64, annex_capacity=256,
            min_trigger_pad=32, record_capacity=record_capacity))
        for w in windows:
            op.add_window_assigner(w)
        op.add_aggregation(agg)
        op.set_max_lateness(lateness)
        return op
    return build


def assert_counters_match(p, n_iv, make_oracle, with_late_face=True):
    d = p.device_metrics()
    n_tup, n_late, hist = replay_lateness(p, n_iv, with_late_face)
    assert d["device_ingest_tuples"] == n_tup, (
        "ingest", d["device_ingest_tuples"], n_tup)
    assert d["device_late_tuples"] == n_late, (
        "late", d["device_late_tuples"], n_late)
    got_hist = [d[n] for n in dev.late_bucket_names()]
    assert got_hist == hist.tolist(), ("strata", got_hist, hist.tolist())
    assert sum(got_hist) == d["device_late_tuples"]
    assert d["device_dropped_tuples"] == 0
    triggers, nonempty = oracle_trigger_counts(make_oracle, p, n_iv,
                                               with_late_face)
    assert d["device_triggers_fired"] == triggers, (
        "triggers", d["device_triggers_fired"], triggers)
    assert d["device_windows_nonempty"] == nonempty, (
        "nonempty", d["device_windows_nonempty"], nonempty)


# ---------------------------------------------------------------------------
# The three OOO-capable fused pipelines vs the oracle
# ---------------------------------------------------------------------------


def test_stream_pipeline_counters_match_simulator():
    from scotty_tpu.engine.pipeline import StreamPipeline

    windows = [TumblingWindow(Time, 50)]
    agg = SumAggregation()
    p = StreamPipeline(windows, [agg], config=CFG, throughput=30_000,
                       wm_period_ms=100, max_lateness=100, seed=3,
                       sub_batch=1 << 10, out_of_order_pct=0.1)
    p.run(3, collect=False)
    p.sync()
    assert_counters_match(p, 3, make_sim(windows, agg, 100))


@pytest.mark.parametrize("agg_factory", [SumAggregation,
                                         lambda: HyperLogLogAggregation(6)])
def test_aligned_pipeline_counters_match_simulator(agg_factory):
    """Both late folds: dense aggs take the scatter-free SEGMENT fold,
    sparse (HLL) aggs the bounded lane-scatter fold — each must agree
    with the same arrival-order oracle."""
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    windows = [TumblingWindow(Time, 50), SlidingWindow(Time, 200, 50)]
    agg = agg_factory()
    p = AlignedStreamPipeline(
        windows, [agg], config=CFG, throughput=20_000, wm_period_ms=100,
        max_lateness=100, seed=5, gc_every=10 ** 9, out_of_order_pct=0.1)
    p.run(4, collect=False)
    p.sync()
    assert_counters_match(p, 4, make_sim(windows, agg, 100))


def test_count_pipeline_counters_inorder_match_simulator():
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    windows = [TumblingWindow(Count, 7), TumblingWindow(Time, 50)]
    agg = SumAggregation()
    p = CountStreamPipeline(windows, [agg], throughput=2000,
                            wm_period_ms=100, max_lateness=100, seed=3)
    p.run(5, collect=False)
    p.sync()
    assert_counters_match(p, 5, make_sim(windows, agg, 100))


def test_count_pipeline_counters_ooo_match_engine_oracle():
    """OOO count: the device operator is the trigger oracle (the
    simulator's TreeSet dedup drops the stratified stream's equal-ts
    records — a reproduced reference artifact, not pipeline behavior)."""
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    windows = [TumblingWindow(Count, 7), TumblingWindow(Time, 50)]
    agg = SumAggregation()
    p = CountStreamPipeline(windows, [agg], throughput=2000,
                            wm_period_ms=100, max_lateness=300, seed=3,
                            out_of_order_pct=0.3)
    p.run(5, collect=False)
    p.check_overflow()
    p.sync()
    assert_counters_match(
        p, 5, make_dev_op(windows, agg, 300, record_capacity=1 << 12))


# ---------------------------------------------------------------------------
# Session pipeline + invariants
# ---------------------------------------------------------------------------


def test_session_pipeline_counters():
    """Ingest/silence are closed-form-checkable; triggers/nonempty must
    equal what the pipeline itself emitted (every completed session is a
    non-empty window)."""
    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    p = SessionStreamPipeline(
        [SessionWindow(Time, 300), SlidingWindow(Time, 500, 100)],
        [SumAggregation()], config=CFG, throughput=20_000,
        wm_period_ms=100, max_lateness=100, seed=2,
        session_config={"count": 3, "minGapMs": 300, "maxGapMs": 700})
    fetched = jax.device_get(p.run(12))
    p.sync()
    d = p.device_metrics()
    assert d["device_ingest_tuples"] == sum(
        len(p.materialize_interval(i)[0]) for i in range(12))
    assert d["device_silent_intervals"] == sum(
        0 if p.live(i) else 1 for i in range(12))
    emitted = sum(int((np.asarray(f[2]) > 0).sum()) for f in fetched)
    assert d["device_windows_nonempty"] == emitted
    assert d["device_triggers_fired"] >= emitted
    assert d["device_late_tuples"] == 0


def test_collect_device_metrics_off_is_inert():
    """The A/B flag: metrics off must produce BIT-IDENTICAL window
    results (the telemetry can never perturb the data path) and leave
    the pytree at zero."""
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def run(flag):
        p = AlignedStreamPipeline(
            [TumblingWindow(Time, 50)], [SumAggregation()], config=CFG,
            throughput=20_000, wm_period_ms=100, max_lateness=100, seed=9,
            gc_every=10 ** 9, out_of_order_pct=0.1,
            collect_device_metrics=flag)
        outs = jax.device_get(p.run(3))
        p.sync()
        return outs, p.device_metrics()

    on_outs, on_dm = run(True)
    off_outs, off_dm = run(False)
    for a, b in zip(on_outs, off_outs):
        for x, y in zip(a[:3], b[:3]):
            assert np.array_equal(np.asarray(x), np.asarray(y))
    assert sum(off_dm.values()) == 0
    assert on_dm["device_ingest_tuples"] > 0


def test_device_metrics_fold_into_registry():
    """sync() folds the delta into the registry under the device_*
    names; attaching obs mid-run baselines at the attach point."""
    from scotty_tpu import obs as obs_mod
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()], config=CFG,
        throughput=20_000, wm_period_ms=100, seed=1, gc_every=10 ** 9)
    p.run(2, collect=False)
    p.sync()                                   # pre-attach ("warmup")
    obs = obs_mod.Observability()
    p.set_observability(obs)
    p.run(3, collect=False)
    p.sync()
    snap = obs.snapshot()
    # only the post-attach intervals folded (2000 tuples/interval)
    assert snap[dev.DEVICE_INGEST_TUPLES] == 3 * p.tuples_per_interval
    assert snap[dev.DEVICE_TRIGGERS_FIRED] > 0


# ---------------------------------------------------------------------------
# Operator ingest paths
# ---------------------------------------------------------------------------


def test_operator_device_batches_counters_match_replay():
    """Device-resident batches: ts are host-opaque, so the jitted cummax
    kernel is the only exact source — it must agree with a host replay
    of the same arrays."""
    import jax.numpy as jnp

    B = 64
    # no Observability attached -> force collection (default is AUTO:
    # a bare operator stays zero-overhead)
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 12, batch_size=B, annex_capacity=256,
        min_trigger_pad=32), collect_device_metrics=True)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)

    rng = np.random.default_rng(5)
    lo, batches = 0, []
    for _ in range(6):
        base = np.sort(rng.integers(lo, lo + 100, size=B)).astype(np.int64)
        late = rng.random(B) < 0.2
        ts = np.sort(np.where(late, np.maximum(
            base - rng.integers(0, 80, size=B), 0), base)).astype(np.int64)
        vals = rng.integers(1, 9, size=B).astype(np.float32)
        op.ingest_device_batch(jax.device_put(jnp.asarray(vals)),
                               jax.device_put(jnp.asarray(ts)),
                               int(ts.min()), int(ts.max()))
        batches.append(ts)
        lo += 100
    op.process_watermark(lo + 500)
    d = op.device_metrics()
    met = np.iinfo(np.int64).min
    late, ages = 0, []
    for ts in batches:
        for t in ts:
            if t < met:
                late += 1
                ages.append(met - t)
            met = max(met, int(t))
    assert d["device_ingest_tuples"] == 6 * B
    assert d["device_late_tuples"] == late
    assert [d.get(n, 0) for n in dev.late_bucket_names()] == \
        dev.host_late_age_hist(ages).tolist()


def test_operator_auto_mode_collects_only_with_obs():
    """Default AUTO: a bare operator (no Observability) collects nothing
    — zero-overhead contract preserved; attaching obs turns it on."""
    from scotty_tpu import obs as obs_mod

    def feed(op):
        op.add_window_assigner(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        op.process_elements(np.arange(50, dtype=np.float32),
                            np.arange(50, dtype=np.int64))
        op.process_watermark(100)

    bare = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, batch_size=64))
    feed(bare)
    assert bare.device_metrics() == {}

    watched = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 10, batch_size=64), obs=obs_mod.Observability())
    feed(watched)
    assert watched.device_metrics()["device_ingest_tuples"] == 50


def test_operator_host_batches_counters_match_replay():
    op = TpuWindowOperator(config=EngineConfig(
        capacity=1 << 12, batch_size=64, annex_capacity=256,
        min_trigger_pad=32), collect_device_metrics=True)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    rng = np.random.default_rng(7)
    base = np.cumsum(rng.integers(0, 5, size=300)).astype(np.int64)
    ts = np.maximum(base - rng.integers(0, 40, size=300), 0)
    vals = rng.integers(1, 50, size=300).astype(np.float32)
    op.process_elements(vals, ts)
    op.process_watermark(int(ts.max()) + 1)
    d = op.device_metrics()
    met = np.iinfo(np.int64).min
    late, ages = 0, []
    for t in ts:
        if t < met:
            late += 1
            ages.append(met - t)
        met = max(met, int(t))
    assert d["device_ingest_tuples"] == 300
    assert d["device_late_tuples"] == late
    assert [d.get(n, 0) for n in dev.late_bucket_names()] == \
        dev.host_late_age_hist(ages).tolist()


# ---------------------------------------------------------------------------
# obs diff gate (tier-1, ISSUE 2 satellite)
# ---------------------------------------------------------------------------


def _cells(tps):
    return [{"name": "t", "windows": "Tumbling(1000)", "engine": "TpuEngine",
             "aggregation": "sum", "tuples_per_sec": tps,
             "p99_emit_ms": 5.0, "windows_emitted": 10}]


def test_obs_diff_exits_zero_on_identical(tmp_path):
    from scotty_tpu.obs.diff import diff_main

    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    pa.write_text(json.dumps(_cells(1e9)))
    pb.write_text(json.dumps(_cells(1e9)))
    assert diff_main(str(pa), str(pb), echo=lambda s: None) == 0


def test_obs_diff_fails_on_injected_throughput_regression(tmp_path):
    """A 10% throughput drop must trip the default gate (rel_tol 0.10 is
    the boundary; 10.01% clears it strictly)."""
    from scotty_tpu.obs.diff import diff_main

    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    pa.write_text(json.dumps(_cells(1e9)))
    pb.write_text(json.dumps(_cells(1e9 * 0.8999)))
    assert diff_main(str(pa), str(pb), echo=lambda s: None) == 1


def test_obs_diff_gates_appearing_resilience_counters(tmp_path):
    """ISSUE 3: the resilience counters are created lazily, so a clean
    FAIL-policy baseline export has no key at all — a candidate that
    STARTED shedding must still trip the default gate (the threshold
    spec's ``default: 0`` covers the absent side)."""
    from scotty_tpu.obs.diff import diff_main

    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    base = _cells(1e9)
    cand = json.loads(json.dumps(base))
    cand[0]["metrics"] = {"resilience_shed_tuples": 10_000}
    pa.write_text(json.dumps(base))
    pb.write_text(json.dumps(cand))
    assert diff_main(str(pa), str(pb), echo=lambda s: None) == 1
    # and the reverse (counter vanishing) is not a regression
    pa.write_text(json.dumps(cand))
    pb.write_text(json.dumps(base))
    assert diff_main(str(pa), str(pb), echo=lambda s: None) == 0


def test_obs_diff_cli_and_thresholds(tmp_path, capsys):
    """End-to-end through the module CLI with a custom threshold file,
    plus missing-cell detection."""
    from scotty_tpu.obs.report import main as obs_main

    pa = tmp_path / "a.json"
    pb = tmp_path / "b.json"
    th = tmp_path / "th.json"
    pa.write_text(json.dumps(_cells(1e9) + [
        dict(_cells(1e9)[0], windows="Sliding(60,20)")]))
    pb.write_text(json.dumps(_cells(0.97e9)))   # -3% + one cell dropped
    th.write_text(json.dumps(
        {"metrics": {"tuples_per_sec":
                     {"direction": "higher", "rel_tol": 0.05}}}))
    # -3% within tolerance, but the dropped cell regresses
    assert obs_main(["diff", str(pa), str(pb),
                     "--thresholds", str(th)]) == 1
    out = capsys.readouterr().out
    assert "missing from candidate" in out
    # same single cell, within tolerance: passes
    pa.write_text(json.dumps(_cells(1e9)))
    assert obs_main(["diff", str(pa), str(pb),
                     "--thresholds", str(th)]) == 0


def test_runner_gate_flag(tmp_path):
    """--gate end to end: first run records the baseline (exit 0), an
    injected regression in the baseline file makes the rerun fail."""
    from scotty_tpu.bench.runner import main as bench_main

    cfg = tmp_path / "tiny.json"
    cfg.write_text(json.dumps({
        "name": "gatetiny", "throughput": 20_000, "runtime": 2,
        "windowConfigurations": ["Tumbling(100)"],
        "configurations": ["TpuEngine"], "aggFunctions": ["sum"],
        "watermarkPeriodMs": 100, "capacity": 4096,
    }))
    out = tmp_path / "out"
    assert bench_main([str(cfg), "--out-dir", str(out),
                       "--gate", "default"]) == 0   # no baseline yet
    # doctor the recorded result into an inflated baseline -> rerun regresses
    res_path = out / "result_gatetiny.json"
    rows = json.loads(res_path.read_text())
    rows[0]["tuples_per_sec"] *= 100.0
    base_dir = tmp_path / "base"
    base_dir.mkdir()
    (base_dir / "result_gatetiny.json").write_text(json.dumps(rows))
    assert bench_main([str(cfg), "--out-dir", str(out),
                       "--gate", "default",
                       "--baseline-dir", str(base_dir)]) == 1


# ---------------------------------------------------------------------------
# Legacy-generator anchor cell (ADVICE r5)
# ---------------------------------------------------------------------------


def test_legacy_generator_anchor_cell():
    """The pinned r4-workload generator: 32-bit value draws + a real
    offset stream. Window values must match a brute-force recomputation
    over the materialized (offset-bearing) stream."""
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [TumblingWindow(Time, 50), SlidingWindow(Time, 200, 50)],
        [SumAggregation()], config=CFG, throughput=20_000,
        wm_period_ms=100, seed=7, gc_every=10 ** 9, legacy_generator=True)
    outs = p.run(3)
    vs, ts = [], []
    for i in range(3):
        v, t = p.materialize_interval(i)
        vs.append(v)
        ts.append(t)
    vs, ts = np.concatenate(vs), np.concatenate(ts)
    assert np.unique(ts).size > 200      # offsets really exist
    checked = 0
    for (s, e, c, vals) in p.lowered_results(outs[-1]):
        m = (ts >= s) & (ts < e)
        if not m.any():
            continue
        checked += 1
        assert c == int(m.sum())
        want = float(vs[m].sum())
        assert abs(float(vals[0]) - want) <= 2e-4 * max(1.0, abs(want))
    assert checked > 0


def test_legacy_anchor_config_bundled():
    """The pinned anchor config ships with the runner and routes to the
    aligned pipeline with the legacy generator."""
    import os

    from scotty_tpu.bench import load_config

    here = os.path.join(os.path.dirname(
        __import__("scotty_tpu.bench", fromlist=["runner"]).__file__),
        "configurations", "legacy_anchor.json")
    cfg = load_config(here)
    assert cfg.legacy_generator
    assert cfg.configurations == ["TpuEngine"]
