"""Observability subsystem: span nesting/export, Prometheus exposition,
JSONL exporter + report CLI, bench-result metrics round-trip (ISSUE 1)."""

import json

from scotty_tpu.obs import (
    INGEST_TUPLES,
    JsonlExporter,
    Observability,
    SpanRecorder,
    prometheus_text,
)
from scotty_tpu.obs.report import main as report_main, render, summarize


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------


def test_span_nesting_and_summary():
    rec = SpanRecorder()
    with rec.span("outer"):
        with rec.span("inner"):
            pass
        with rec.span("inner"):
            pass
    assert [s.name for s in rec.spans] == ["inner", "inner", "outer"]
    depths = {s.name: s.depth for s in rec.spans}
    assert depths == {"inner": 1, "outer": 0}
    summ = rec.summary()
    assert summ["inner"]["count"] == 2
    assert summ["outer"]["count"] == 1
    # children close inside the parent: total child time <= parent time
    assert summ["inner"]["total_ms"] <= summ["outer"]["total_ms"] + 1e-6


def test_span_chrome_trace_export(tmp_path):
    rec = SpanRecorder()
    with rec.span("ingest"):
        with rec.span("query"):
            pass
    events = rec.to_chrome_trace()
    assert all(e["ph"] == "X" for e in events)
    q, i = events[0], events[1]
    assert (q["name"], i["name"]) == ("query", "ingest")
    # nested event lies within the parent interval (µs timestamps)
    assert i["ts"] <= q["ts"]
    assert q["ts"] + q["dur"] <= i["ts"] + i["dur"] + 1.0
    path = tmp_path / "trace.json"
    rec.dump_chrome_trace(str(path))
    obj = json.loads(path.read_text())
    assert len(obj["traceEvents"]) == 2


def test_span_bounded():
    rec = SpanRecorder(max_spans=3)
    for _ in range(10):
        with rec.span("s"):
            pass
    assert len(rec.spans) == 3
    assert rec.summary()["_dropped_spans"] == 7


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------


def test_prometheus_exposition_format():
    obs = Observability()
    obs.counter(INGEST_TUPLES).inc(42)
    obs.gauge("slice_occupancy").set(0.5)
    obs.histogram("emit_latency_ms").observe(3.0)
    text = obs.prometheus()
    assert "# TYPE scotty_ingest_tuples counter" in text
    assert "scotty_ingest_tuples 42.0" in text
    assert "# TYPE scotty_slice_occupancy gauge" in text
    assert "# TYPE scotty_emit_latency_ms summary" in text
    assert 'scotty_emit_latency_ms{quantile="0.5"} 3.0' in text
    assert "scotty_emit_latency_ms_count 1" in text
    # every non-comment line is "name[{labels}] value"
    for line in text.strip().splitlines():
        if not line.startswith("#"):
            name, value = line.rsplit(" ", 1)
            assert name and float(value) is not None


def test_prometheus_empty_registry_is_empty_exposition():
    assert prometheus_text(Observability().registry) == ""


def test_prometheus_zero_observation_histogram():
    """A summary with no observations must not fabricate 0-valued
    quantiles: NaN quantiles (the Prometheus convention), honest
    ``_sum``/``_count``."""
    obs = Observability()
    obs.histogram("emit_latency_ms")
    text = obs.prometheus()
    assert "# TYPE scotty_emit_latency_ms summary" in text
    assert 'scotty_emit_latency_ms{quantile="0.5"} nan' in text
    assert "scotty_emit_latency_ms_sum 0.0" in text
    assert "scotty_emit_latency_ms_count 0" in text


def test_prometheus_type_lines_once_per_family():
    """Two raw names sanitizing to one family: ONE ``# TYPE`` line, ONE
    sample — a duplicate unlabeled sample for a series is an invalid
    exposition a scraper rejects wholesale, so later same-family metrics
    (same type OR conflicting type) are dropped with an explicit comment,
    never silently."""
    obs = Observability()
    obs.counter("late.tuples").inc(1)          # both sanitize to
    obs.counter("late_tuples").inc(2)          # scotty_late_tuples
    obs.gauge("late tuples").set(9.0)          # same family, other type
    text = obs.prometheus()
    assert text.count("# TYPE scotty_late_tuples ") == 1
    assert "# TYPE scotty_late_tuples counter" in text
    samples = [ln for ln in text.splitlines()
               if ln.startswith("scotty_late_tuples ")]
    assert samples == ["scotty_late_tuples 1.0"]   # first wins, no dupes
    assert text.count("dropped metric") == 2       # both drops announced


def test_prometheus_help_and_name_sanitization():
    from scotty_tpu.obs.exporters import escape_help, escape_label_value

    obs = Observability()
    obs.counter("1weird metric-name").inc(3)
    text = prometheus_text(
        obs.registry,
        help_texts={"1weird metric-name": "line1\nline2 \\ done"})
    # sanitized family: leading digit guarded, bad chars underscored
    assert "scotty__1weird_metric_name 3.0" in text
    assert "# HELP scotty__1weird_metric_name line1\\nline2 \\\\ done" \
        in text
    assert escape_help("a\nb\\c") == "a\\nb\\\\c"
    assert escape_label_value('say "hi"\n') == 'say \\"hi\\"\\n'


def test_report_degrades_gracefully_on_truncated_jsonl(tmp_path, capsys):
    """The crashed-run export (ISSUE 4 satellite): a half-written final
    line is counted and skipped, never raised."""
    path = tmp_path / "crashed.jsonl"
    path.write_text(
        json.dumps({"t": 1.0, "ingest_tuples": 5.0}) + "\n"
        + json.dumps({"t": 2.0, "ingest_tuples": 9.0}) + "\n"
        + '{"t": 3.0, "ingest_tup')          # torn mid-write
    summary = summarize(str(path))
    assert summary["kind"] == "jsonl"
    assert summary["rows"] == 2
    assert summary["skipped_lines"] == 1
    assert summary["metrics"]["ingest_tuples"]["last"] == 9.0
    out = render(str(path))
    assert "skipped: 1 truncated/corrupt line(s)" in out
    assert report_main(["report", str(path)]) == 0
    assert "skipped" in capsys.readouterr().out

    # a torn single-object export and a torn bench list degrade too
    (tmp_path / "torn.json").write_text('{"ingest_tuples": 5')
    assert summarize(str(tmp_path / "torn.json"))["rows"] == 0
    (tmp_path / "torn_list.json").write_text('[{"name": "x"}')
    assert summarize(str(tmp_path / "torn_list.json"))["kind"] == "jsonl"
    # binary garbage: skipped, not a UnicodeDecodeError
    (tmp_path / "bin.jsonl").write_bytes(b"\xff\xfe{not json}\n")
    assert summarize(str(tmp_path / "bin.jsonl"))["skipped_lines"] == 1


def test_diff_gates_flight_and_health_counters(tmp_path):
    """ISSUE 4 satellite: the default thresholds gate the operational
    counters — wraparound drops or unhealthy verdicts APPEARING in a
    candidate regress even though a clean baseline never exported the
    keys."""
    from scotty_tpu.obs.diff import DEFAULT_THRESHOLDS, diff_exports

    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    base.write_text(json.dumps({"tuples_per_sec": 100.0}))
    cand.write_text(json.dumps({"tuples_per_sec": 100.0,
                                "flight_dropped_events": 5.0,
                                "health_unhealthy": 2.0}))
    findings = diff_exports(str(base), str(cand), DEFAULT_THRESHOLDS)
    regressed = {f["metric"] for f in findings
                 if f["status"] == "regressed"}
    assert "flight_dropped_events" in regressed
    assert "health_unhealthy" in regressed
    # clean both ways stays clean
    cand.write_text(json.dumps({"tuples_per_sec": 100.0}))
    findings = diff_exports(str(base), str(cand), DEFAULT_THRESHOLDS)
    assert not [f for f in findings if f["status"] == "regressed"]


def test_jsonl_exporter_and_report(tmp_path):
    path = tmp_path / "metrics.jsonl"
    obs = Observability()
    obs.counter(INGEST_TUPLES).inc(100)
    obs.write_jsonl(str(path), label="cell-0")
    obs.counter(INGEST_TUPLES).inc(50)
    obs.write_jsonl(str(path), label="cell-1")

    summary = summarize(str(path))
    assert summary["kind"] == "jsonl"
    assert summary["rows"] == 2
    st = summary["metrics"][INGEST_TUPLES]
    assert (st["min"], st["max"], st["last"]) == (100.0, 150.0, 150.0)

    out = render(str(path))
    assert INGEST_TUPLES in out and "150" in out


def test_report_cli_end_to_end(tmp_path, capsys):
    path = tmp_path / "metrics.jsonl"
    with JsonlExporter(str(path)) as ex:
        ex.write({"ingest_tuples": 7.0, "watermark_lag_ms": 12.0}, t=1.0)
    assert report_main(["report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "watermark_lag_ms" in out
    assert report_main(["report", str(path), "--json"]) == 0
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["metrics"]["ingest_tuples"]["last"] == 7.0


def test_report_reads_chrome_trace(tmp_path):
    rec = SpanRecorder()
    with rec.span("timed"):
        pass
    path = tmp_path / "trace.json"
    rec.dump_chrome_trace(str(path))
    summary = summarize(str(path))
    assert summary["kind"] == "chrome-trace"
    assert summary["spans"]["timed"]["count"] == 1


def test_report_reads_bench_result_cells(tmp_path):
    cells = [{"name": "x", "windows": "Tumbling(1000)", "engine": "T",
              "aggregation": "sum", "tuples_per_sec": 1e6,
              "metrics": {"metrics": {"ingest_tuples": 5.0},
                          "spans": {"timed": {"count": 1, "total_ms": 2.0,
                                              "mean_ms": 2.0,
                                              "max_ms": 2.0}}}}]
    path = tmp_path / "result_x.json"
    path.write_text(json.dumps(cells))
    summary = summarize(str(path))
    assert summary["kind"] == "bench-result"
    assert summary["cells"][0]["metrics"]["ingest_tuples"] == 5.0
    assert "ingest_tuples" in render(str(path))


# ---------------------------------------------------------------------------
# engine + bench integration
# ---------------------------------------------------------------------------


def test_operator_telemetry_hooks():
    import numpy as np

    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.operator import TpuWindowOperator

    obs = Observability()
    op = TpuWindowOperator(config=EngineConfig(
        capacity=128, annex_capacity=16, batch_size=4), obs=obs)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(100)
    op.process_elements(np.asarray([1.0, 2.0, 3.0, 4.0], np.float32),
                        np.asarray([1, 5, 12, 18], np.int64))
    # a late batch (below the stream's max event time)
    op.process_elements(np.asarray([9.0, 9.0, 9.0, 9.0], np.float32),
                        np.asarray([2, 3, 25, 30], np.int64))
    op.process_watermark(20)
    op.check_overflow()
    snap = obs.snapshot()
    assert snap["ingest_tuples"] == 8
    assert snap["late_tuples"] == 2
    assert snap["watermarks"] == 1
    assert snap["watermark_lag_ms"] == 30 - 20
    assert snap["watermark_dispatch_ms_count"] == 1
    assert 0 < snap["slice_occupancy"] <= 1
    assert snap["slice_headroom"] < 128


def test_connector_telemetry():
    from scotty_tpu.connectors.base import KeyedScottyWindowOperator
    from scotty_tpu.connectors.iterable import collect_keyed
    from scotty_tpu.core.aggregates import SumAggregation
    from scotty_tpu.core.windows import TumblingWindow, WindowMeasure

    obs = Observability()
    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(WindowMeasure.Time, 10)],
        aggregations=[SumAggregation()], obs=obs)
    stream = [("a", 1.0, t) for t in range(0, 40, 2)]
    out = collect_keyed(stream, op, final_watermark=100)
    assert out
    snap = obs.snapshot()
    assert snap["ingest_tuples"] == len(stream)
    assert snap["watermarks"] >= 1
    assert snap["windows_emitted"] >= len(out) - 1


def test_run_benchmark_metrics_roundtrip(tmp_path):
    """A small bench run embeds a metrics section in to_dict() and its
    exports summarize end-to-end (ISSUE 1 acceptance)."""
    from scotty_tpu.bench.harness import BenchmarkConfig, run_benchmark

    cfg = BenchmarkConfig(name="obs-rt", throughput=4096, runtime_s=2,
                          batch_size=1024, capacity=1 << 10,
                          watermark_period_ms=500)
    res = run_benchmark(cfg, "Tumbling(500)", "sum", engine="TpuEngine",
                        warmup_batches=1)
    d = res.to_dict()
    assert "metrics" in d
    m = d["metrics"]["metrics"]
    assert m["ingest_tuples"] > 0
    assert m["watermarks"] >= 1
    assert d["metrics"]["spans"]["stream"]["count"] == 1
    # JSON-serializable end to end (the result artifact contract)
    json.dumps(d)

    # exports + report CLI round-trip
    jl = tmp_path / "m.jsonl"
    tr = tmp_path
    res.observability.write_jsonl(str(jl), label="cell")
    res.observability.write_chrome_trace(str(tr / "t.json"))
    assert summarize(str(jl))["metrics"]["ingest_tuples"]["last"] > 0
    assert summarize(str(tr / "t.json"))["spans"]["stream"]["count"] == 1

    # disabled observability: no metrics section, no registry work
    res_off = run_benchmark(cfg, "Tumbling(500)", "sum",
                            engine="TpuEngine", warmup_batches=0,
                            collect_metrics=False)
    assert "metrics" not in res_off.to_dict()
