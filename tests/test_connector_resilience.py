"""Connector hardening (ISSUE 3): poison records/dead-letter, retrying
sources, stall watchdogs, queue-depth sampling, connector snapshots —
all chaos driven and deterministic (seeded injectors, ManualClock).
"""

import asyncio

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.connectors.base import (
    AscendingWatermarks,
    KeyedScottyWindowOperator,
)
from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
from scotty_tpu.obs import Observability
from scotty_tpu.resilience import (
    FlakySource,
    ManualClock,
    PoisonLimitExceeded,
    SourceExhaustedRetries,
    SourceStalled,
    StallingSource,
    backoff_delay,
    corrupt_records,
    make_records,
    retrying_source,
    watchdog_source,
)

Time = WindowMeasure.Time


def keyed_op(obs=None):
    return KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 100)],
        aggregations=[SumAggregation()],
        watermark_policy=AscendingWatermarks(), obs=obs)


# -- kafka poison path (ISSUE 3 satellite: malformed-record regression) ----

def test_kafka_malformed_record_no_longer_kills_run():
    """Seed bug: a payload that is neither JSON nor numeric raised an
    uncaught ValueError out of _default_deserialize and killed run().
    It now routes through the poison/dead-letter path and the stream
    keeps flowing."""
    records, bad_idx = corrupt_records(make_records(seed=7, n=60), seed=8,
                                       pct=0.1)
    obs = Observability()
    adapter = KafkaScottyWindowOperator(operator=keyed_op(obs=obs))
    letters = []
    results = []
    n = adapter.run(records, results.append,
                    dead_letter=lambda rec, exc: letters.append((rec, exc)))
    assert n == 60                          # every record consumed
    assert len(letters) == len(bad_idx)
    assert all(isinstance(e, Exception) for _, e in letters)
    assert {id(r) for r, _ in letters} == {id(records[i]) for i in bad_idx}
    assert obs.registry.snapshot()["resilience_poison_records"] == len(bad_idx)
    assert results                          # clean records still windowed


def test_kafka_malformed_record_without_dead_letter_still_flows():
    records, bad_idx = corrupt_records(make_records(seed=7, n=40), seed=9,
                                       pct=0.1)
    adapter = KafkaScottyWindowOperator(operator=keyed_op())
    assert adapter.run(records, lambda item: None) == 40


def test_kafka_poison_limit():
    records, _ = corrupt_records(make_records(seed=7, n=30), seed=10, pct=0.5)
    adapter = KafkaScottyWindowOperator(operator=keyed_op())
    with pytest.raises(PoisonLimitExceeded):
        adapter.run(records, lambda item: None, poison_limit=3)


def test_iterable_poison_records_are_dead_lettered():
    from scotty_tpu.connectors.iterable import run_keyed

    good = [("a", 1.0, t * 10) for t in range(20)]
    src = good[:5] + [("a", 1.0), None, ("a", 1.0, "NaN-ish")] + good[5:]
    letters = []
    out = list(run_keyed(src, keyed_op(),
                         dead_letter=lambda r, e: letters.append(r)))
    assert len(letters) == 3
    assert out                              # stream survived the poison


# -- retrying source -------------------------------------------------------

def test_retrying_source_resumes_from_last_good_offset():
    records = list(range(20))
    flaky = FlakySource(records, fail_at={5, 11})
    obs = Observability()
    clock = ManualClock()
    got = list(retrying_source(flaky, max_retries=3, clock=clock, obs=obs,
                               seed=2))
    assert got == records                   # nothing lost, nothing doubled
    assert flaky.failures == [5, 11]
    assert obs.registry.snapshot()["resilience_source_retries"] == 2
    # each failure had made progress since the last → attempt resets to 1
    rng = np.random.default_rng(2)
    assert clock.sleeps == [
        pytest.approx(backoff_delay(1, 0.05, 2.0, 0.5, rng)),
        pytest.approx(backoff_delay(1, 0.05, 2.0, 0.5, rng))]


def test_retrying_source_exhausts_on_persistent_failure():
    def dead_source(offset):
        raise ConnectionError("down")
        yield                               # pragma: no cover

    with pytest.raises(SourceExhaustedRetries) as ei:
        list(retrying_source(dead_source, max_retries=2,
                             clock=ManualClock()))
    assert isinstance(ei.value.__cause__, ConnectionError)


# -- stall watchdog --------------------------------------------------------

def test_watchdog_flags_exactly_the_injected_stalls():
    clock = ManualClock()
    src = StallingSource(list(range(30)), stall_at={7, 19}, stall_s=5.0,
                         clock=clock)
    obs = Observability()
    gaps = []
    got = list(watchdog_source(src, stall_timeout_s=1.0, clock=clock,
                               obs=obs, on_stall=gaps.append))
    assert got == list(range(30))
    assert obs.registry.snapshot()["resilience_stall_events"] == 2
    assert [pytest.approx(g) for g in gaps] == [5.0, 5.0]


def test_watchdog_ignores_slow_consumer():
    """The stall window measures only the SOURCE pull — a consumer that
    spends longer than the stall budget processing each record must not
    be misreported as a producer stall."""
    clock = ManualClock()
    obs = Observability()
    wd = watchdog_source(iter(range(10)), stall_timeout_s=1.0, clock=clock,
                         obs=obs)
    got = []
    for item in wd:
        got.append(item)
        clock.advance(10.0)                 # heavy per-record processing
    assert got == list(range(10))
    assert "resilience_stall_events" not in obs.registry.snapshot()


def test_corrupt_records_pct_zero_is_a_clean_control_arm():
    records, idx = corrupt_records(make_records(seed=1, n=10), seed=2,
                                   pct=0.0)
    assert idx == []
    records, idx = corrupt_records(make_records(seed=1, n=10), seed=2,
                                   pct=0.01)
    assert len(idx) == 1                    # positive pct floors at one


def test_kafka_run_with_watchdog():
    clock = ManualClock()
    records = make_records(seed=3, n=20)
    src = StallingSource(records, stall_at={10}, stall_s=9.0, clock=clock)
    obs = Observability()
    adapter = KafkaScottyWindowOperator(operator=keyed_op(obs=obs))
    adapter.run(src, lambda item: None, stall_timeout_s=2.0, clock=clock)
    assert obs.registry.snapshot()["resilience_stall_events"] == 1


# -- asyncio queue source (ISSUE 3 satellite: depth gauge + stalls) --------

def test_queue_source_samples_depth_after_get_and_throttled():
    from scotty_tpu.connectors.asyncio_connector import queue_source

    async def main():
        obs = Observability()
        q = asyncio.Queue()
        for i in range(40):
            q.put_nowait(("k", 1.0, i * 10))
        q.put_nowait(None)                  # sentinel
        seen = 0
        async for _ in queue_source(q, obs=obs, depth_sample_every=8):
            seen += 1
        return seen, obs.registry.snapshot()["queue_depth"]

    seen, depth = asyncio.run(main())
    assert seen == 40
    # sampled AFTER the final (sentinel) get: an idle consumer reports the
    # drained queue, not the stale pre-wait depth (seed bug)
    assert depth == 0


def test_queue_source_stall_watchdog_preempts():
    from scotty_tpu.connectors.asyncio_connector import queue_source

    async def main():
        obs = Observability()
        q = asyncio.Queue()                 # never fed: a stalled producer
        stalls = []
        with pytest.raises(SourceStalled):
            async for _ in queue_source(q, obs=obs, stall_timeout_s=0.01,
                                        on_stall=stalls.append,
                                        max_stalls=2):
                pass                        # pragma: no cover
        return stalls, obs.registry.snapshot()["resilience_stall_events"]

    stalls, n = asyncio.run(main())
    assert len(stalls) == 2 and n == 2


# -- connector snapshot/restore --------------------------------------------

def test_keyed_connector_save_restore_continues_identically(tmp_path):
    stream = [(f"k{i % 3}", float(i % 7), i * 25) for i in range(80)]

    def feed(op, items):
        out = []
        for k, v, t in items:
            out.extend((kk, w.start, w.end, tuple(w.agg_values))
                       for kk, w in op.process_element(k, v, t))
        return out

    ref = keyed_op()
    ref_out = feed(ref, stream)

    op1 = keyed_op()
    head = feed(op1, stream[:40])
    op1.save(str(tmp_path / "conn"))
    op2 = keyed_op()
    op2.restore(str(tmp_path / "conn"))
    tail = feed(op2, stream[40:])
    assert head + tail == ref_out


def test_keyed_connector_restore_rejects_mismatched_lateness(tmp_path):
    op = keyed_op()
    op.process_element("a", 1.0, 10)
    op.save(str(tmp_path / "conn"))
    other = KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 100)],
        aggregations=[SumAggregation()], allowed_lateness=77)
    with pytest.raises(ValueError, match="allowed_lateness"):
        other.restore(str(tmp_path / "conn"))
