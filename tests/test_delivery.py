"""Exactly-once delivery (ISSUE 8): the TransactionalSink contract, the
epoch ledger's atomic ride inside checkpoint bundles, the connector
run-loop sink wiring, and supervised exactly-once recovery
(`delivery.run_supervised`) across all three run loops — plus the
interleaved A/B bound on what the ledger costs the iterable loop."""

import os
import time

import pytest

from scotty_tpu import obs as _obs
from scotty_tpu.connectors.base import (AscendingWatermarks,
                                        KeyedScottyWindowOperator)
from scotty_tpu.core.aggregates import SumAggregation
from scotty_tpu.core.windows import TumblingWindow, WindowMeasure
from scotty_tpu.delivery import (AT_LEAST_ONCE, EXACTLY_ONCE, EpochLedger,
                                 TransactionalSink, asyncio_segment,
                                 kafka_segment, run_supervised)
from scotty_tpu.resilience.chaos import ChaosError
from scotty_tpu.resilience.clock import ManualClock
from scotty_tpu.resilience.supervisor import Supervisor


def make_op(obs=None):
    return KeyedScottyWindowOperator(
        windows=[TumblingWindow(WindowMeasure.Time, 100)],
        aggregations=[SumAggregation()],
        watermark_policy=AscendingWatermarks(), obs=obs)


def keyed_records(n, keys=3):
    return [(f"k{i % keys}", float(i), i * 10) for i in range(n)]


class OneShotCrashSource:
    """Replayable indexable source that raises ONCE at an absolute
    offset — the supervised-restart fodder (a FlakySource that supports
    the ``records[offset:]`` slicing run_supervised uses)."""

    def __init__(self, records, crash_at):
        self.records = records
        self.crash_at = set(int(c) for c in crash_at)

    def __len__(self):
        return len(self.records)

    def __getitem__(self, sl):
        parent = self

        class _View:
            def __iter__(self_view):
                base = sl.start or 0
                for i, r in enumerate(parent.records[sl]):
                    if base + i in parent.crash_at:
                        parent.crash_at.discard(base + i)
                        raise ChaosError(
                            f"injected crash at offset {base + i}")
                    yield r

        return _View()


# -- the sink contract -------------------------------------------------------

def test_sink_rejects_unknown_mode():
    with pytest.raises(ValueError, match="at_least_once"):
        TransactionalSink(mode="twice_for_luck")


def test_at_least_once_never_suppresses():
    sink = TransactionalSink(mode=AT_LEAST_ONCE)
    assert all(sink.emit(i) for i in range(5))
    sink.delivered = 100                     # even behind the high-water
    assert sink.emit("again")
    assert sink.suppressed == 0


def test_exactly_once_suppresses_replay_below_horizon():
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    assert [sink.emit(i) for i in range(4)] == [True] * 4
    # a supervised restart replays from seq 0 (no checkpoint yet)
    sink.restore(None)
    assert [sink.emit(i) for i in range(6)] == \
        [False, False, False, False, True, True]
    assert sink.suppressed == 4
    assert sink.delivered == 5


def test_sink_restore_rewinds_to_ledger(tmp_path):
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    for i in range(7):
        sink.emit(i)
    sink.save(str(tmp_path))                 # ledger: epoch 1, seq 6
    sink.on_commit(7)
    sink.emit(7)                             # past the checkpoint
    sink.restore(str(tmp_path))
    assert sink.epoch == 1
    assert sink.next_seq == 7                # rewound to committed head
    assert sink.delivered == 7               # horizon NOT rewound
    assert sink.emit("replayed-7") is False  # the in-flight one suppressed
    assert sink.emit("new-8") is True


def test_drain_into_hands_off_per_item():
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    out = []

    class Boom(RuntimeError):
        pass

    real_emit = sink.emit

    def emit(item):
        if item == "c":
            raise Boom()
        return real_emit(item)

    sink.emit = emit
    with pytest.raises(Boom):
        sink.drain_into(["a", "b", "c", "d"], out.append)
    # items sequenced before the crash reached the collector — the batch
    # face would have discarded them (the crash-point sweep's finding)
    assert out == ["a", "b"]


def test_sink_counters_and_flight(tmp_path):
    obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=64))
    sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
    for i in range(3):
        sink.emit(i)
    sink.restore(None)
    sink.emit(0)
    snap = obs.snapshot()
    assert snap[_obs.DELIVERY_EMITTED] == 3
    assert snap[_obs.DELIVERY_DUPLICATES_SUPPRESSED] == 1
    kinds = [ev["kind"] for ev in obs.flight.snapshot()["events"]]
    assert "emit" in kinds and "duplicate_suppressed" in kinds


# -- the ledger --------------------------------------------------------------

def test_ledger_round_trip(tmp_path):
    EpochLedger(epoch=3, committed_seq=41).save(str(tmp_path))
    back = EpochLedger.load(str(tmp_path))
    assert (back.epoch, back.committed_seq) == (3, 41)


def test_ledger_missing_is_none(tmp_path):
    assert EpochLedger.load(str(tmp_path)) is None


def test_ledger_rejects_foreign_schema(tmp_path):
    with open(os.path.join(str(tmp_path), "ledger.json"), "w") as f:
        f.write('{"schema": "not_a_ledger/9", "epoch": 0, '
                '"committed_seq": -1}')
    with pytest.raises(ValueError, match="not a delivery ledger"):
        EpochLedger.load(str(tmp_path))


def test_ledger_commits_inside_the_bundle_manifest(tmp_path):
    """The atomicity claim, checked from disk: ledger.json lands in the
    SAME sealed bundle as state+offset, covered by the manifest — one
    commit point, no torn (state, offset, delivered-seq) triples."""
    from scotty_tpu.utils.checkpoint import verify_checkpoint

    sup = Supervisor(str(tmp_path), clock=ManualClock())
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    run_supervised(keyed_records(40), make_op, sup, sink=sink,
                   checkpoint_every=20, final_watermark=10_000)
    gens = [n for n in os.listdir(str(tmp_path)) if n.startswith("ckpt-")
            and ".tmp" not in n]
    assert gens
    for g in gens:
        d = os.path.join(str(tmp_path), g)
        assert verify_checkpoint(d)["ok"] is True
        assert EpochLedger.load(d) is not None
        import json

        with open(os.path.join(d, "MANIFEST.json")) as f:
            assert "ledger.json" in json.load(f)["files"]


# -- connector run-loop wiring ----------------------------------------------

def test_iterable_run_keyed_sink_suppresses():
    from scotty_tpu.connectors.iterable import run_keyed

    recs = keyed_records(40)
    baseline = list(run_keyed(iter(recs), make_op()))
    assert baseline
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    sink.delivered = len(baseline) // 2 - 1  # pretend half already landed
    out = list(run_keyed(iter(recs), make_op(), sink=sink))
    assert out == baseline[len(baseline) // 2:]
    assert sink.suppressed == len(baseline) // 2


def test_iterable_run_global_sink_suppresses():
    from scotty_tpu.connectors.base import GlobalScottyWindowOperator
    from scotty_tpu.connectors.iterable import run_global

    def g_op():
        return GlobalScottyWindowOperator(
            windows=[TumblingWindow(WindowMeasure.Time, 100)],
            aggregations=[SumAggregation()],
            watermark_policy=AscendingWatermarks())

    recs = [(float(i), i * 10) for i in range(40)]
    baseline = list(run_global(iter(recs), g_op()))
    assert baseline
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    sink.delivered = 0                        # first emission already landed
    out = list(run_global(iter(recs), g_op(), sink=sink))
    assert out == baseline[1:]
    assert sink.suppressed == 1


def test_kafka_run_sink_suppresses():
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
    from scotty_tpu.resilience.chaos import make_records

    recs = make_records(seed=3, n=60, keys=3)
    out_a, out_b = [], []
    KafkaScottyWindowOperator(make_op()).run(recs, out_a.append)
    assert out_a
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    sink.delivered = 1                        # first two already landed
    KafkaScottyWindowOperator(make_op()).run(recs, out_b.append, sink=sink)
    assert out_b == out_a[2:]
    assert sink.suppressed == 2


def test_asyncio_run_sink_suppresses():
    import asyncio

    from scotty_tpu.connectors.asyncio_connector import run_keyed_async

    recs = keyed_records(40)

    async def source():
        for r in recs:
            yield r

    def run(sink=None):
        out = []

        async def main():
            await run_keyed_async(source(), make_op(), emit=out.append,
                                  sink=sink)

        asyncio.run(main())
        return out

    baseline = run()
    assert baseline
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    sink.delivered = 0
    assert run(sink) == baseline[1:]
    assert sink.suppressed == 1


# -- supervised exactly-once recovery ----------------------------------------

ORACLE_RECORDS = keyed_records(120)


def _oracle(tmp_path, segment=None):
    sup = Supervisor(os.path.join(str(tmp_path), "oracle"),
                     clock=ManualClock())
    return run_supervised(ORACLE_RECORDS, make_op, sup,
                          sink=TransactionalSink(mode=EXACTLY_ONCE),
                          checkpoint_every=32, run_segment=segment,
                          final_watermark=10_000)


def test_run_supervised_exactly_once_across_crashes(tmp_path):
    oracle = _oracle(tmp_path)
    assert oracle
    obs = _obs.Observability(flight=_obs.FlightRecorder(capacity=1024))
    sup = Supervisor(os.path.join(str(tmp_path), "crashy"),
                     clock=ManualClock(), obs=obs, max_restarts=5)
    sink = TransactionalSink(mode=EXACTLY_ONCE, obs=obs)
    out = run_supervised(OneShotCrashSource(ORACLE_RECORDS, [50, 90]),
                         make_op, sup, sink=sink, checkpoint_every=32,
                         final_watermark=10_000)
    assert out == oracle                     # bit-identical, zero dupes
    assert sink.suppressed > 0               # the replays really happened
    assert obs.snapshot()[_obs.DELIVERY_DUPLICATES_SUPPRESSED] \
        == sink.suppressed


def test_run_supervised_at_least_once_duplicates_demonstrated(tmp_path):
    """The control arm: WITHOUT the exactly-once ledger the same crash
    re-emits every post-checkpoint emission — the silent-duplicate
    failure mode the delivery layer exists to close."""
    oracle = _oracle(tmp_path)
    sup = Supervisor(os.path.join(str(tmp_path), "alo"),
                     clock=ManualClock(), max_restarts=5)
    out = run_supervised(OneShotCrashSource(ORACLE_RECORDS, [50]),
                         make_op, sup,
                         sink=TransactionalSink(mode=AT_LEAST_ONCE),
                         checkpoint_every=32, final_watermark=10_000)
    assert len(out) > len(oracle)            # duplicates delivered
    # every oracle item is present; the excess is replayed duplicates
    rest = list(out)
    for item in oracle:
        rest.remove(item)
    assert rest                              # the duplicates themselves
    for dup in rest:
        assert dup in oracle


def test_run_supervised_kafka_segment(tmp_path):
    from scotty_tpu.resilience.chaos import _Record

    kafka_records = [_Record(f"k{i % 3}", str(i), i * 10)
                     for i in range(120)]

    def seg_oracle():
        sup = Supervisor(os.path.join(str(tmp_path), "ko"),
                         clock=ManualClock())
        return run_supervised(
            kafka_records, make_op, sup,
            sink=TransactionalSink(mode=EXACTLY_ONCE),
            checkpoint_every=32, run_segment=kafka_segment(),
            final_watermark=10_000)

    oracle = seg_oracle()
    assert oracle
    sup = Supervisor(os.path.join(str(tmp_path), "kc"),
                     clock=ManualClock(), max_restarts=5)
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    out = run_supervised(OneShotCrashSource(kafka_records, [85]),
                         make_op, sup, sink=sink, checkpoint_every=32,
                         run_segment=kafka_segment(),
                         final_watermark=10_000)
    assert out == oracle
    assert sink.suppressed > 0


def test_run_supervised_asyncio_segment(tmp_path):
    def seg_oracle():
        sup = Supervisor(os.path.join(str(tmp_path), "ao"),
                         clock=ManualClock())
        return run_supervised(
            ORACLE_RECORDS, make_op, sup,
            sink=TransactionalSink(mode=EXACTLY_ONCE),
            checkpoint_every=32, run_segment=asyncio_segment(),
            final_watermark=10_000)

    oracle = seg_oracle()
    assert oracle
    sup = Supervisor(os.path.join(str(tmp_path), "ac"),
                     clock=ManualClock(), max_restarts=5)
    sink = TransactionalSink(mode=EXACTLY_ONCE)
    out = run_supervised(OneShotCrashSource(ORACLE_RECORDS, [85]),
                         make_op, sup, sink=sink, checkpoint_every=32,
                         run_segment=asyncio_segment(),
                         final_watermark=10_000)
    assert out == oracle
    assert sink.suppressed > 0


def test_run_supervised_gives_up_raises(tmp_path):
    from scotty_tpu.resilience.supervisor import SupervisorGaveUp

    sup = Supervisor(os.path.join(str(tmp_path), "doom"),
                     clock=ManualClock(), max_restarts=2)
    with pytest.raises(SupervisorGaveUp):
        run_supervised(
            OneShotCrashSource(ORACLE_RECORDS, [10, 11, 12, 13, 14, 15]),
            make_op, sup, sink=TransactionalSink(mode=EXACTLY_ONCE),
            checkpoint_every=1000, final_watermark=10_000)


# -- the cost of the ledger --------------------------------------------------

def test_exactly_once_ledger_overhead_bounded():
    """Interleaved A/B on the iterable loop (the ISSUE 8 acceptance
    bound): the exactly-once sink's per-emission cost — one int compare
    + two increments — must stay ≤ 2% median against the bare loop."""
    from scotty_tpu.connectors.iterable import run_keyed

    recs = keyed_records(3000, keys=8)

    def once(with_sink):
        op = make_op()
        sink = TransactionalSink(mode=EXACTLY_ONCE) if with_sink else None
        t0 = time.perf_counter()
        n = sum(1 for _ in run_keyed(iter(recs), op, sink=sink))
        dt = time.perf_counter() - t0
        return n, dt

    once(False), once(True)                  # warm both paths
    # median-of-medians over interleaved pairs; retried because a busy
    # CI box can skew any single timing trial either way
    ratios = []
    for _trial in range(3):
        a_times, b_times = [], []
        for _ in range(15):
            n_a, dt_a = once(False)
            n_b, dt_b = once(True)
            assert n_a == n_b
            a_times.append(dt_a)
            b_times.append(dt_b)
        a_times.sort()
        b_times.sort()
        ratios.append(b_times[len(b_times) // 2]
                      / a_times[len(a_times) // 2])
        if ratios[-1] <= 1.02:
            break
    assert min(ratios) <= 1.02, (
        f"exactly-once ledger overhead "
        f"{100 * (min(ratios) - 1):.2f}% median exceeds the 2% bound "
        f"(trial ratios: {ratios})")
