"""Fixed-band window operator tests — transliterated from
slicing/src/test/.../windowTest/FixedBandWindowTest.java."""

import pytest

from scotty_tpu import (
    FixedBandWindow,
    SumAggregation,
    WindowMeasure,
)
from conftest import make_operator
from window_assert import assert_window


@pytest.fixture(params=["host", "engine"])
def op(request):
    return make_operator(request.param)


def sum_fn():
    # same host semantics as ReduceAggregateFunction(a+b), plus a device
    # realization — the goldens drive both operators (conftest.make_operator)
    return SumAggregation()


def test_in_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 1, 10))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(55)
    assert_window(results[0], 1, 11, 1)


def test_in_order_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 0, 10))
    op.process_element(1, 0)
    op.process_element(2, 0)
    op.process_element(3, 20)
    op.process_element(4, 30)
    op.process_element(5, 40)

    results = op.process_watermark(22)
    assert_window(results[0], 0, 10, 3)

    results = op.process_watermark(55)
    assert results == []


def test_in_order_3(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 18, 10))
    op.process_element(1, 0)
    op.process_element(2, 0)
    op.process_element(3, 20)
    op.process_element(4, 30)
    op.process_element(5, 40)

    results = op.process_watermark(22)
    assert results == []

    results = op.process_watermark(55)
    assert_window(results[0], 18, 28, 3)


def test_in_order_two_windows(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 10, 10))
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 20, 10))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 2

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3


def test_in_order_two_windows_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 14, 11))
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 23, 10))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(26)
    assert results[0].get_agg_values()[0] == 2

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3


def test_in_order_two_windows_dynamic(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 10, 10))

    op.process_element(1, 1)
    op.process_element(2, 19)
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 20, 10))
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 2

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 3


def test_in_order_two_windows_dynamic_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 10, 10))

    op.process_element(1, 1)
    op.process_element(2, 19)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 2

    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 20, 21))
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 7


def test_out_of_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(FixedBandWindow(WindowMeasure.Time, 10, 20))
    op.process_element(1, 1)
    op.process_element(1, 29)

    # out-of-order tuples have to be inserted into the window
    op.process_element(1, 20)
    op.process_element(1, 23)
    op.process_element(1, 25)

    op.process_element(1, 45)

    results = op.process_watermark(22)
    assert results == []

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 4
