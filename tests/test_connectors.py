"""Connector-layer tests (the reference has none — SURVEY.md §4 notes
connectors are only validated via demos; we cover them properly)."""

import asyncio

import pytest

from scotty_tpu import (
    MeanAggregation,
    SessionWindow,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.connectors import (
    AscendingWatermarks,
    GlobalScottyWindowOperator,
    KeyedScottyWindowOperator,
    PeriodicWatermarks,
    collect_global,
    collect_keyed,
)

Time = WindowMeasure.Time


def test_keyed_host_backend_tumbling():
    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(Time, 10))
          .add_aggregation(SumAggregation())
          .with_allowed_lateness(1))
    src = [("a", 1, 1), ("b", 10, 2), ("a", 2, 5), ("b", 20, 7),
           ("a", 3, 12), ("b", 30, 15), ("a", 4, 21), ("b", 40, 25)]
    results = collect_keyed(src, op, final_watermark=40)
    by_key = {}
    for k, w in results:
        by_key.setdefault(k, []).append((w.get_start(), w.get_end(),
                                         w.get_agg_values()[0]))
    assert (0, 10, 3) in by_key["a"]
    assert (10, 20, 3) in by_key["a"]
    assert (20, 30, 4) in by_key["a"]
    assert (0, 10, 30) in by_key["b"]
    assert (10, 20, 30) in by_key["b"]
    assert (20, 30, 40) in by_key["b"]


def test_keyed_session_windows_via_connector():
    op = (KeyedScottyWindowOperator()
          .add_window(SessionWindow(Time, 5))
          .add_aggregation(SumAggregation()))
    src = [("k", 1, 0), ("k", 2, 2), ("k", 4, 20), ("k", 8, 22)]
    results = collect_keyed(src, op, final_watermark=100)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for _, w in results]
    assert (0, 7, 3) in wins
    assert (20, 27, 12) in wins


def test_global_connector():
    op = (GlobalScottyWindowOperator()
          .add_window(SlidingWindow(Time, 10, 5))
          .add_aggregation(MeanAggregation()))
    src = [(2, 1), (4, 3), (6, 8), (8, 12), (10, 18)]
    results = collect_global(src, op, final_watermark=30)
    wins = {(w.get_start(), w.get_end()): w.get_agg_values()[0]
            for w in results}
    assert wins[(0, 10)] == pytest.approx(4.0)       # 2, 4, 6


def test_periodic_watermark_policy():
    p = PeriodicWatermarks(period=100)
    assert p.observe(0) is None
    assert p.observe(50) is None
    assert p.observe(101) == 101
    assert p.observe(150) is None
    assert p.observe(202) == 202


def test_ascending_watermark_policy_with_delay():
    p = AscendingWatermarks(delay=10)
    assert p.observe(5) is None        # 5-10 < initial watermark
    assert p.observe(3) is None        # no regress
    assert p.observe(50) == 40
    assert p.observe(45) is None       # 35 < 40


def test_asyncio_connector():
    from scotty_tpu.connectors.asyncio_connector import (
        queue_source, run_keyed_async)

    async def main():
        q = asyncio.Queue()
        for item in [("x", 1, 1), ("x", 2, 5), ("x", 3, 12), ("x", 4, 25)]:
            q.put_nowait(item)
        q.put_nowait(None)
        op = (KeyedScottyWindowOperator()
              .add_window(TumblingWindow(Time, 10))
              .add_aggregation(SumAggregation()))
        got = []
        await run_keyed_async(queue_source(q), op, got.append)
        got.extend(op.process_watermark(100))
        return got

    got = asyncio.run(main())
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for _, w in got]
    assert (0, 10, 3) in wins
    assert (10, 20, 3) in wins
    assert (20, 30, 4) in wins


def test_torchdata_connector():
    torch = pytest.importorskip("torch")
    from scotty_tpu.connectors.torchdata import WindowedResultDataset

    rows = [("k", 1.0, 1), ("k", 2.0, 5), ("k", 3.0, 12), ("k", 4.0, 25)]
    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(Time, 10))
          .add_aggregation(SumAggregation()))
    ds = WindowedResultDataset(rows, op, final_watermark=100)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for _, w in ds]
    assert (0, 10, 3.0) in wins
    assert (20, 30, 4.0) in wins


def test_kafka_adapter_with_fake_records():
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator

    class FakeRecord:
        def __init__(self, key, value, ts):
            self.key = key.encode()
            self.value = str(value).encode()
            self.timestamp = ts

    records = [FakeRecord("k", 1, 0), FakeRecord("k", 2, 50),
               FakeRecord("k", 3, 250), FakeRecord("k", 4, 500)]
    op = KafkaScottyWindowOperator()
    op.operator.add_window(TumblingWindow(Time, 100))
    op.operator.add_aggregation(SumAggregation())
    got = []
    n = op.run(records, got.append)
    got.extend(op.operator.process_watermark(1000))
    assert n == 4
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for _, w in got]
    # Reference semantics corner: the first watermark fires at ts 250 with
    # lateness 1, so windows fully before 249 never trigger
    # (WindowManager.java:43-45); and because the slicer only materializes
    # edges from max(te - maxLateness, lastEdge) (StreamSlicer.java:103-116),
    # the ts-250 record lands in slice [100, 300) which the [200, 300)
    # window does not contain — only the ts-500 record's window emits.
    assert wins == [(500, 600, 4.0)]


def test_spark_adapter_partition_mapper():
    from scotty_tpu.connectors.spark import scotty_flat_map

    mapper = scotty_flat_map(
        windows=[TumblingWindow(Time, 10)],
        aggregations=[SumAggregation()],
        watermark_period_ms=5)
    part = [("k", 1, 1), ("k", 2, 5), ("k", 3, 12), ("k", 4, 30)]
    out = list(mapper(part))
    # first watermark fires at ts 12 → [10, 20) emits on the ts-30 tick
    assert ("k", 10, 20, (3,)) in out


def test_beam_dofn_without_beam_installed():
    from scotty_tpu.connectors.beam import ScottyWindowDoFn

    fn = ScottyWindowDoFn(windows=[TumblingWindow(Time, 10)],
                          aggregations=[SumAggregation()],
                          watermark_period_ms=5)
    fn.setup()
    out = []
    for element in [("k", (1, 1)), ("k", (2, 5)), ("k", (3, 12)),
                    ("k", (4, 30))]:
        out.extend(fn.process(element))
    assert any("0-10" in s or "0, 10" in s or "WindowResult" in s for s in out)


def test_spark_map_in_pandas_matches_host_operator():
    """The mapInPandas-shaped mapper (structured-streaming path) emits the
    same windows as driving the host operator directly."""
    import pandas as pd

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.connectors.spark import scotty_map_in_pandas

    windows = [TumblingWindow(WindowMeasure.Time, 10)]
    aggs = [SumAggregation()]
    data = [("a", 1, 1), ("a", 2, 5), ("b", 7, 8), ("a", 3, 12),
            ("a", 4, 25), ("b", 1, 26), ("a", 5, 40)]
    df = pd.DataFrame(data, columns=["key", "value", "ts"])
    # allowed_lateness must span the first window or the first watermark's
    # clamp drops it (the reference connector's 1 ms default does exactly
    # that — KeyedScottyWindowOperator.java:26)
    mapper = scotty_map_in_pandas(windows, aggs, allowed_lateness=100,
                                  watermark_period_ms=10)

    out = pd.concat(list(mapper(iter([df]))), ignore_index=True)
    # windows [0,10): a=3, b=7 fire once the stream passes ts>=20 etc.
    got = {(r.key, r.window_start, r.window_end): r.agg_0
           for r in out.itertuples()}
    assert got[("a", 0, 10)] == 3.0
    assert got[("b", 0, 10)] == 7.0
    assert got[("a", 10, 20)] == 3.0


def test_spark_attach_requires_pyspark():
    import pytest as _pytest

    from scotty_tpu import SumAggregation
    from scotty_tpu.connectors.spark import result_schema

    with _pytest.raises(ImportError, match="pyspark"):
        result_schema([SumAggregation()])


def test_flink_adapter_engine_watermarks():
    """The flink adapter uses the engine watermark when it advances and
    falls back to element ts otherwise
    (flink-connector KeyedScottyWindowOperator.java:72-86)."""
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.connectors.flink import KeyedScottyWindowOperator

    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(WindowMeasure.Time, 10))
          .add_aggregation(SumAggregation())
          .allowed_lateness(100))

    assert op.process_record("a", 1, 1, current_watermark=None) == []
    assert op.process_record("a", 2, 5, current_watermark=0) == []
    # engine watermark advances past the first window: [0,10) emits
    rows = op.process_record("a", 3, 12, current_watermark=11)
    assert ("a", 0, 10, (3,)) in rows
    # element-ts fallback (no engine watermark = NEGATIVE, the reference's
    # currentWatermark()<0 test — watermark 0 is VALID and must not fall
    # back, ADVICE r2): ts 25 fires [10,20)
    rows = op.process_record("a", 4, 25, current_watermark=-1)
    assert any(r[1] == 10 and r[2] == 20 and r[3] == (3,) for r in rows)


def test_flink_global_adapter():
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.connectors.flink import GlobalScottyWindowOperator

    op = (GlobalScottyWindowOperator(allowed_lateness=100)
          .add_window(TumblingWindow(WindowMeasure.Time, 10)))
    op.add_aggregation(SumAggregation())
    op.process_record(1, 1)
    op.process_record(2, 5)
    rows = op.process_record(3, 15)
    assert rows == [(0, 10, (3,))]


def test_keyed_connector_device_backend():
    """backend="device" routes the connector through the batched
    KeyedTpuWindowOperator (keys hashed onto shard lanes); same windows as
    the host backend for a keyed stream."""
    from scotty_tpu.engine import EngineConfig

    src = [("a", 1, 1), ("b", 10, 2), ("a", 2, 5), ("b", 20, 7),
           ("a", 3, 12), ("b", 30, 15), ("a", 4, 21), ("b", 40, 25),
           ("a", 5, 33), ("b", 50, 41)]

    def run(backend):
        op = KeyedScottyWindowOperator(
            backend=backend, n_key_shards=8,
            engine_config=EngineConfig(capacity=512, batch_size=16,
                                       annex_capacity=64,
                                       min_trigger_pad=32))
        op.add_window(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        op.with_allowed_lateness(100)
        got = []
        for k, v, t in src:
            got.extend(op.process_element(k, v, t))
        got.extend(op.process_watermark(100))
        return got

    host = run("host")
    dev = run("device")
    # device results are keyed by shard id, host by original key — compare
    # the multiset of (start, end, value) windows
    h = sorted((w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
               for _, w in host)
    d = sorted((w.get_start(), w.get_end(), float(w.get_agg_values()[0]))
               for _, w in dev)
    assert h == d, (h, d)


def test_keyed_connector_device_backend_preserves_keys():
    """Distinct keys get distinct device lanes (hashing would merge
    colliding keys' windows) and results come back under the ORIGINAL key;
    exceeding n_key_shards distinct keys is an explicit error."""
    from scotty_tpu.engine import EngineConfig

    op = KeyedScottyWindowOperator(
        backend="device", n_key_shards=2,
        engine_config=EngineConfig(capacity=512, batch_size=8,
                                   annex_capacity=64, min_trigger_pad=32))
    op.add_window(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.with_allowed_lateness(100)
    for k, v, t in [("x", 1, 1), ("y", 10, 2), ("x", 2, 5), ("y", 20, 8)]:
        op.process_element(k, v, t)
    got = op.process_watermark(50)
    by_key = {k: float(w.get_agg_values()[0]) for k, w in got
              if w.has_value()}
    assert by_key == {"x": 3.0, "y": 30.0}

    with pytest.raises(RuntimeError, match="n_key_shards"):
        op.process_element("z", 1, 9)


def test_global_connector_device_backend():
    """GlobalScottyWindowOperator with backend="device" routes through the
    sharded GlobalTpuWindowOperator; totals match the host backend."""
    from scotty_tpu.engine import EngineConfig

    def run(backend):
        op = GlobalScottyWindowOperator(
            backend=backend, n_shards=4,
            engine_config=EngineConfig(capacity=512, batch_size=16,
                                       annex_capacity=64,
                                       min_trigger_pad=32))
        op.add_window(TumblingWindow(Time, 10))
        op.add_aggregation(SumAggregation())
        op.allowed_lateness = 100
        got = []
        for v, t in [(1, 1), (2, 5), (3, 12), (4, 18), (5, 25), (6, 33)]:
            got.extend(op.process_element(v, t))
        got.extend(op.process_watermark(50))
        return sorted((w.get_start(), w.get_end(),
                       float(w.get_agg_values()[0])) for w in got)

    assert run("host") == run("device")


def test_torch_dataloader_runs_adapter_inside_real_framework():
    """The torch connector driven by torch's ACTUAL execution engine — a
    real ``torch.utils.data.DataLoader`` iterating the windowed dataset —
    not just the adapter called directly (VERDICT r3 item 10: at least one
    connector exercised inside its live host framework)."""
    torch = pytest.importorskip("torch")
    from torch.utils.data import DataLoader

    from scotty_tpu.connectors.torchdata import WindowedResultDataset

    rows = [("k", 1.0, 1), ("k", 2.0, 5), ("k", 3.0, 12), ("k", 4.0, 25)]
    op = (KeyedScottyWindowOperator()
          .add_window(TumblingWindow(Time, 10))
          .add_aggregation(SumAggregation()))
    ds = WindowedResultDataset(rows, op, final_watermark=100)
    # collate_fn=identity: window results are (key, AggregateWindow) pairs
    loader = DataLoader(ds, batch_size=None, collate_fn=lambda x: x)
    wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
            for _, w in loader]
    assert (0, 10, 3.0) in wins
    assert (20, 30, 4.0) in wins


def test_beam_pipeline_runs_adapter_inside_real_framework():
    """Skip-if-missing: when apache_beam is installed, run ScottyWindowDoFn
    inside a REAL DirectRunner pipeline (not just DoFn methods called
    directly). Skips in environments without beam — the point is that the
    smoke test exists and runs wherever the framework does."""
    beam = pytest.importorskip("apache_beam")

    from scotty_tpu.connectors.beam import ScottyWindowDoFn

    def check(windows_list):
        wins = [(w.get_start(), w.get_end(), w.get_agg_values()[0])
                for _, w in windows_list]
        assert (0, 10, 3.0) in wins, wins
        assert (20, 30, 4.0) in wins, wins
        return True

    rows = [("k", 1.0, 1), ("k", 2.0, 5), ("k", 3.0, 12), ("k", 4.0, 25)]
    with beam.Pipeline() as p:
        _ = (p
             | beam.Create(rows)
             | beam.ParDo(ScottyWindowDoFn(
                 windows=[TumblingWindow(Time, 10)],
                 aggregations=[SumAggregation()],
                 final_watermark=100))
             | beam.combiners.ToList()
             | beam.Map(check))


def test_flink_pipeline_runs_adapter_inside_real_framework():
    """Skip-if-missing: when pyflink is installed, run the keyed adapter
    inside a REAL local StreamExecutionEnvironment."""
    pytest.importorskip("pyflink")
    from pyflink.common import Types
    from pyflink.datastream import StreamExecutionEnvironment

    from scotty_tpu.connectors.flink import KeyedScottyWindowOperator as F

    env = StreamExecutionEnvironment.get_execution_environment()
    env.set_parallelism(1)
    ds = env.from_collection([("k", 1.0, 1), ("k", 2.0, 5), ("k", 3.0, 12)],
                             type_info=Types.TUPLE(
                                 [Types.STRING(), Types.FLOAT(),
                                  Types.LONG()]))
    fn = F(windows=[TumblingWindow(Time, 10)],
           aggregations=[SumAggregation()])
    ds.key_by(lambda r: r[0]).process(fn)
    env.execute("scotty-smoke")
