"""Generic device path for user-defined forward-context-aware windows.

The dual-face contract (core ``ForwardContextAware.device_context_spec``
↔ host ``create_context``) is pinned differentially on two axes:

* **Bounds** — emitted window ``[start, end)`` sets must equal the
  simulator's (the host face runs the reference context calculus +
  slice repair, WindowContext.java:9-107, SliceManager.java:89-166).
* **Values** — the engine must report the EXACT per-window aggregate,
  checked against an independent scalar replay of the capped-session
  calculus in this file. The simulator's values are NOT the value
  oracle for capped sessions: a cap-declined extension opens a new
  session within ``gap`` of its predecessor, so the predecessor's
  emitted window overlaps the successor's span, and the reference's
  geometric slice containment then double-counts or drops tuples
  (PARITY.md deviation 5 — slice-granularity artifacts the engine
  deliberately does not reproduce).

CappedSessionWindow is the shipped example user window (VERDICT r3
item 1b: general context-aware windows device-native).
"""

import numpy as np
import pytest

from scotty_tpu import (
    CappedSessionWindow,
    MaxAggregation,
    SessionWindow,
    SlicingWindowOperator,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator

from test_engine_differential import SMALL, compare

Time = WindowMeasure.Time


# ---------------------------------------------------------------------------
# exact scalar oracle for capped sessions (independent of jax and of the
# host face — a third implementation of the same calculus)
# ---------------------------------------------------------------------------


class _ExactCapped:
    def __init__(self, gap, cap):
        self.gap, self.cap = gap, cap
        self.s = []          # [first, last, values] sorted by first
        self.orphans = []    # (pos, value)

    def add(self, v, t):
        g, cap, s = self.gap, self.cap, self.s
        hit = None
        for i, (f, l, vs) in enumerate(s):
            if f - g <= t <= l + g:
                hit = i
                break
            if f - g > t:
                break
        if hit is None:
            self._insert(t, t, [v])
            return
        f, l, vs = s[hit]
        if f <= t <= l:
            vs.append(v)
            return
        if t < f:                       # start-extension
            if l - t > cap:
                self._insert(t, t, [v])
                return
            s[hit][0] = t
            vs.append(v)
            if hit > 0 and s[hit - 1][1] + g >= t \
                    and l - s[hit - 1][0] <= cap:
                pf, pl, pvs = s.pop(hit - 1)
                s[hit - 1][0] = pf
                s[hit - 1][2] = pvs + s[hit - 1][2]
            return
        if t <= l + g:                  # end-extension
            if t - f > cap:
                self._insert(t, t, [v])
                return
            s[hit][1] = t
            vs.append(v)
            if hit + 1 < len(s) and t + g >= s[hit + 1][0] \
                    and s[hit + 1][1] - f <= cap:
                nf, nl, nvs = s.pop(hit + 1)
                s[hit][1] = nl
                s[hit][2] = s[hit][2] + nvs
            return
        self.orphans.append((t, v))     # exact-gap fall-through

    def _insert(self, f, l, vs):
        k = 0
        while k < len(self.s) and self.s[k][0] <= f:
            k += 1
        self.s.insert(k, [f, l, vs])

    def sweep(self, wm):
        out = []
        keep = []
        for f, l, vs in self.s:
            if l + self.gap < wm:
                ws, we = f, l + self.gap
                extra = [v for (p, v) in self.orphans if ws <= p < we]
                self.orphans = [(p, v) for (p, v) in self.orphans
                                if not (ws <= p < we)]
                out.append((ws, we, vs + extra))
            else:
                keep.append([f, l, vs])
        self.s = keep
        return out


def drive_capped(stream, wms, gap, cap, extra_windows=(), lateness=1000):
    """Run simulator + engine + exact oracle; check bounds sim==eng==oracle
    per watermark, grid-window values sim==eng, capped values eng==oracle."""
    sim = SlicingWindowOperator()
    eng = TpuWindowOperator(config=SMALL)
    oracle = _ExactCapped(gap, cap)
    for op in (sim, eng):
        op.add_window_assigner(CappedSessionWindow(Time, gap, cap))
        for w in extra_windows:
            op.add_window_assigner(w)
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(lateness)
    pos = 0
    for after, wm in wms:
        while pos <= after and pos < len(stream):
            v, t = stream[pos]
            sim.process_element(float(v), t)
            eng.process_element(float(v), t)
            oracle.add(float(v), t)
            pos += 1
        rs = sim.process_watermark(wm)
        re = eng.process_watermark(wm)
        exp = oracle.sweep(wm)
        assert len(rs) == len(re), (wm, rs, re)
        n_ctx = len(exp)
        grid_s, ctx_s = rs[:len(rs) - n_ctx], rs[len(rs) - n_ctx:]
        grid_e, ctx_e = re[:len(re) - n_ctx], re[len(re) - n_ctx:]
        compare(grid_s, grid_e, wm)            # grid rows: full equality
        for (a, b, (ws, we, vs)) in zip(ctx_s, ctx_e, exp):
            assert (a.get_start(), a.get_end()) == (ws, we), (wm, a, exp)
            assert (b.get_start(), b.get_end()) == (ws, we), (wm, b, exp)
            assert b.has_value() == bool(vs), (wm, b, vs)
            if vs:
                assert float(b.get_agg_values()[0]) == pytest.approx(
                    sum(vs)), (wm, b, vs)
    eng.check_overflow()


def test_capped_session_scripted():
    """Chaining, cap-declined extension (new session 8ms after the last
    tuple — closer than the gap, impossible for plain sessions), and a
    fresh session after a real gap."""
    stream = [(1, 0), (2, 8), (3, 16), (4, 24), (5, 32), (6, 40),
              (7, 100), (8, 108), (9, 150)]
    drive_capped(stream, [(5, 60), (7, 130), (8, 200)], gap=10, cap=30)


def test_capped_session_merge_within_cap():
    """A bridge tuple merges two sessions only when the combined span fits
    the cap."""
    stream = [(1, 0), (2, 4), (3, 20), (4, 24),     # two sessions, gap 10
              (5, 12),                              # bridge: merged span 24
              (6, 100), (7, 104), (8, 130), (9, 134),
              (10, 118),                            # bridge but span 34>30
              (11, 300)]
    drive_capped(stream, [(4, 60), (10, 250), (10, 400)], gap=10, cap=30,
                 lateness=10_000)


def test_capped_session_with_grid_mix():
    """Generic context windows alongside time-grid windows: emission order
    is context-free first, then context-aware (WindowManager.java:98-118);
    grid values stay exact while capped values follow the exact oracle."""
    stream = [(i + 1, i * 6) for i in range(30)]
    stream[12] = (13, 71)       # hold the chain; cap split happens mid-run
    drive_capped(stream, [(9, 40), (19, 100), (29, 250)], gap=15, cap=40,
                 extra_windows=[TumblingWindow(Time, 50)])


@pytest.mark.parametrize("seed", [1, 13, 27])
def test_capped_session_differential(seed):
    """Randomized in-order capped-session streams: bounds vs the
    simulator, values vs the exact oracle."""
    rng = np.random.default_rng(seed)
    n = 120
    ts = np.cumsum(rng.integers(1, 25, size=n)).astype(np.int64)
    vals = rng.integers(1, 60, size=n)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wms = []
    for p in (n // 3, 2 * n // 3, n - 1):
        w = int(ts[p]) + 1
        if not wms or w > wms[-1][1]:
            wms.append((p, w))
    drive_capped(stream, wms, gap=12, cap=45, lateness=10_000)


def test_generic_path_reproduces_tuned_sessions():
    """SessionDecider-family calculus through the generic kernels == the
    tuned session path: a CappedSessionWindow with an unreachable cap IS
    a session, and both engines must emit identically (coherence proof
    for the generic apply/sweep machinery, including out-of-order)."""
    rng = np.random.default_rng(2)
    ts = np.cumsum(rng.integers(1, 30, size=120)).astype(np.int64)
    # mild intra-batch disorder exercises the scan's arrival-order replay
    jig = ts.copy()
    idx = rng.integers(1, 120, 15)
    jig[idx] = np.maximum(jig[idx] - rng.integers(0, 40, 15), 1)
    vals = rng.integers(1, 50, size=120)

    def drive(window):
        op = TpuWindowOperator(config=SMALL)
        op.add_window_assigner(window)
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(10_000)
        out = []
        for lo in range(0, 120, 30):
            op.process_elements(vals[lo:lo + 30].astype(np.float32),
                                jig[lo:lo + 30])
            wm = int(ts[min(lo + 29, 119)])
            out += [(w.start, w.end, round(float(w.agg_values[0]), 3)
                     if w.has_value() else None)
                    for w in op.process_watermark(wm)]
        op.check_overflow()
        return out

    tuned = drive(SessionWindow(Time, 20))
    generic = drive(CappedSessionWindow(Time, 20, 1 << 40))
    assert tuned == generic, (tuned[:5], generic[:5])


def test_hybrid_routes_context_windows():
    """Hybrid: device when the window has a device face, host otherwise."""
    from scotty_tpu.core.windows import ForwardContextAware, WindowContext
    from scotty_tpu.hybrid import HybridWindowOperator

    class HostOnlyContextWindow(ForwardContextAware):
        measure = Time

        def create_context(self):
            return WindowContext()

    dev = HybridWindowOperator(engine_config=SMALL)
    dev.add_window_assigner(CappedSessionWindow(Time, 10, 30))
    dev.add_aggregation(SumAggregation())
    dev.process_element(1.0, 5)
    assert dev.backend == "device"

    host = HybridWindowOperator(engine_config=SMALL)
    host.add_window_assigner(HostOnlyContextWindow())
    host.add_aggregation(SumAggregation())
    host._resolve()
    assert host.backend == "host"


def test_count_measure_context_window_routes_to_host():
    """ADVICE r4 (medium): the device context calculus runs over event
    TIMESTAMPS; count-measure context windows (whose host face — and the
    reference, TupleContext.getTs(measure) — runs over arrival positions)
    must fall back to the host, never silently reach the device."""
    from scotty_tpu.engine.operator import UnsupportedOnDevice
    from scotty_tpu.hybrid import HybridWindowOperator

    Count = WindowMeasure.Count
    w = CappedSessionWindow(Count, 3, 10)
    assert w.device_context_spec() is not None  # spec exists, measure gates

    dev = TpuWindowOperator(config=SMALL)
    with pytest.raises(UnsupportedOnDevice):
        dev.add_window_assigner(w)

    hyb = HybridWindowOperator(engine_config=SMALL)
    hyb.add_window_assigner(CappedSessionWindow(Count, 3, 10))
    hyb.add_aggregation(SumAggregation())
    hyb._resolve()
    assert hyb.backend == "host"


def test_ctx_clear_delay_extends_orphan_retention():
    """ADVICE r4 (low): DeviceContextSpec.clear_delay() participates in
    the sweep's GC bound — retention beyond orphan_reach() is applied as
    slack, so a decider declaring a long clear_delay keeps its orphans
    past wm - max_lateness."""
    op = TpuWindowOperator(config=SMALL)
    op.add_window_assigner(CappedSessionWindow(Time, 10, 30))
    op.add_aggregation(SumAggregation())
    # CappedSessionDecider.clear_delay() = gap + max_span = 40,
    # orphan_reach() = gap = 10 → slack 30
    op.process_element(1.0, 5)
    op.process_watermark(4)            # force build
    assert op._ctx_gc_slack == (30,)
