"""Generic device path for user-defined forward-context-aware windows.

The dual-face contract (core ``ForwardContextAware.device_context_spec``
↔ host ``create_context``) is pinned differentially on two axes:

* **Bounds** — emitted window ``[start, end)`` sets must equal the
  simulator's (the host face runs the reference context calculus +
  slice repair, WindowContext.java:9-107, SliceManager.java:89-166).
* **Values** — the engine must report the EXACT per-window aggregate,
  checked against an independent scalar replay of the capped-session
  calculus in this file. The simulator's values are NOT the value
  oracle for capped sessions: a cap-declined extension opens a new
  session within ``gap`` of its predecessor, so the predecessor's
  emitted window overlaps the successor's span, and the reference's
  geometric slice containment then double-counts or drops tuples
  (PARITY.md deviation 5 — slice-granularity artifacts the engine
  deliberately does not reproduce).

CappedSessionWindow is the shipped example user window (VERDICT r3
item 1b: general context-aware windows device-native).
"""

import numpy as np
import pytest

from scotty_tpu import (
    CappedSessionWindow,
    MaxAggregation,
    SessionWindow,
    SlicingWindowOperator,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator

from test_engine_differential import SMALL, compare

Time = WindowMeasure.Time


# ---------------------------------------------------------------------------
# exact scalar oracle for capped sessions (independent of jax and of the
# host face — a third implementation of the same calculus)
# ---------------------------------------------------------------------------


class _ExactCapped:
    def __init__(self, gap, cap):
        self.gap, self.cap = gap, cap
        self.s = []          # [first, last, values] sorted by first
        self.orphans = []    # (pos, value)

    def add(self, v, t):
        # priority calculus (see CappedContext.update_context): inside >
        # first fitting extension > cap-declined insert; exact-gap-only
        # reach orphans
        g, cap, s = self.gap, self.cap, self.s
        exact = declined = False
        fit_i = -1
        for i, (f, l, vs) in enumerate(s):
            if f <= t <= l:
                vs.append(v)
                return                  # (1) inside
            if f - g <= t <= l + g:
                if t == f - g:
                    exact = True
                elif fit_i < 0 and ((f > t and l - t <= cap)
                                    or (l < t and t - f <= cap)):
                    fit_i = i
                else:
                    declined = True
        if fit_i >= 0:                  # (2) fitting extension
            hit = fit_i
            f, l, vs = s[hit]
            if t < f:                   # start-extension
                s[hit][0] = t
                vs.append(v)
                if hit > 0 and s[hit - 1][1] + g >= t \
                        and l - s[hit - 1][0] <= cap:
                    pf, pl, pvs = s.pop(hit - 1)
                    s[hit - 1][0] = pf
                    s[hit - 1][2] = pvs + s[hit - 1][2]
                return
            s[hit][1] = t               # end-extension
            vs.append(v)
            if hit + 1 < len(s) and t + g >= s[hit + 1][0] \
                    and s[hit + 1][1] - f <= cap:
                nf, nl, nvs = s.pop(hit + 1)
                s[hit][1] = nl
                s[hit][2] = s[hit][2] + nvs
            return
        if declined or not exact:       # (3) declined / out of reach
            self._insert(t, t, [v])
            return
        self.orphans.append((t, v))     # exact-gap fall-through

    def _insert(self, f, l, vs):
        k = 0
        while k < len(self.s) and self.s[k][0] <= f:
            k += 1
        self.s.insert(k, [f, l, vs])

    def sweep(self, wm):
        out = []
        keep = []
        for f, l, vs in self.s:
            if l + self.gap < wm:
                ws, we = f, l + self.gap
                extra = [v for (p, v) in self.orphans if ws <= p < we]
                self.orphans = [(p, v) for (p, v) in self.orphans
                                if not (ws <= p < we)]
                out.append((ws, we, vs + extra))
            else:
                keep.append([f, l, vs])
        self.s = keep
        return out


def drive_capped(stream, wms, gap, cap, extra_windows=(), lateness=1000):
    """Run simulator + engine + exact oracle; check bounds sim==eng==oracle
    per watermark, grid-window values sim==eng, capped values eng==oracle."""
    sim = SlicingWindowOperator()
    eng = TpuWindowOperator(config=SMALL)
    oracle = _ExactCapped(gap, cap)
    for op in (sim, eng):
        op.add_window_assigner(CappedSessionWindow(Time, gap, cap))
        for w in extra_windows:
            op.add_window_assigner(w)
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(lateness)
    pos = 0
    for after, wm in wms:
        while pos <= after and pos < len(stream):
            v, t = stream[pos]
            sim.process_element(float(v), t)
            eng.process_element(float(v), t)
            oracle.add(float(v), t)
            pos += 1
        rs = sim.process_watermark(wm)
        re = eng.process_watermark(wm)
        exp = oracle.sweep(wm)
        assert len(rs) == len(re), (wm, rs, re)
        n_ctx = len(exp)
        grid_s, ctx_s = rs[:len(rs) - n_ctx], rs[len(rs) - n_ctx:]
        grid_e, ctx_e = re[:len(re) - n_ctx], re[len(re) - n_ctx:]
        compare(grid_s, grid_e, wm)            # grid rows: full equality
        for (a, b, (ws, we, vs)) in zip(ctx_s, ctx_e, exp):
            assert (a.get_start(), a.get_end()) == (ws, we), (wm, a, exp)
            assert (b.get_start(), b.get_end()) == (ws, we), (wm, b, exp)
            assert b.has_value() == bool(vs), (wm, b, vs)
            if vs:
                assert float(b.get_agg_values()[0]) == pytest.approx(
                    sum(vs)), (wm, b, vs)
    eng.check_overflow()


def test_capped_session_scripted():
    """Chaining, cap-declined extension (new session 8ms after the last
    tuple — closer than the gap, impossible for plain sessions), and a
    fresh session after a real gap."""
    stream = [(1, 0), (2, 8), (3, 16), (4, 24), (5, 32), (6, 40),
              (7, 100), (8, 108), (9, 150)]
    drive_capped(stream, [(5, 60), (7, 130), (8, 200)], gap=10, cap=30)


def test_capped_session_merge_within_cap():
    """A bridge tuple merges two sessions only when the combined span fits
    the cap."""
    stream = [(1, 0), (2, 4), (3, 20), (4, 24),     # two sessions, gap 10
              (5, 12),                              # bridge: merged span 24
              (6, 100), (7, 104), (8, 130), (9, 134),
              (10, 118),                            # bridge but span 34>30
              (11, 300)]
    drive_capped(stream, [(4, 60), (10, 250), (10, 400)], gap=10, cap=30,
                 lateness=10_000)


def test_capped_session_with_grid_mix():
    """Generic context windows alongside time-grid windows: emission order
    is context-free first, then context-aware (WindowManager.java:98-118);
    grid values stay exact while capped values follow the exact oracle."""
    stream = [(i + 1, i * 6) for i in range(30)]
    stream[12] = (13, 71)       # hold the chain; cap split happens mid-run
    drive_capped(stream, [(9, 40), (19, 100), (29, 250)], gap=15, cap=40,
                 extra_windows=[TumblingWindow(Time, 50)])


@pytest.mark.parametrize("seed", [1, 13, 27])
def test_capped_session_differential(seed):
    """Randomized in-order capped-session streams: bounds vs the
    simulator, values vs the exact oracle."""
    rng = np.random.default_rng(seed)
    n = 120
    ts = np.cumsum(rng.integers(1, 25, size=n)).astype(np.int64)
    vals = rng.integers(1, 60, size=n)
    stream = [(int(v), int(t)) for v, t in zip(vals, ts)]
    wms = []
    for p in (n // 3, 2 * n // 3, n - 1):
        w = int(ts[p]) + 1
        if not wms or w > wms[-1][1]:
            wms.append((p, w))
    drive_capped(stream, wms, gap=12, cap=45, lateness=10_000)


def test_generic_path_reproduces_tuned_sessions():
    """SessionDecider-family calculus through the generic kernels == the
    tuned session path: a CappedSessionWindow with an unreachable cap IS
    a session, and both engines must emit identically (coherence proof
    for the generic apply/sweep machinery, including out-of-order)."""
    rng = np.random.default_rng(2)
    ts = np.cumsum(rng.integers(1, 30, size=120)).astype(np.int64)
    # mild intra-batch disorder exercises the scan's arrival-order replay
    jig = ts.copy()
    idx = rng.integers(1, 120, 15)
    jig[idx] = np.maximum(jig[idx] - rng.integers(0, 40, 15), 1)
    vals = rng.integers(1, 50, size=120)

    def drive(window):
        op = TpuWindowOperator(config=SMALL)
        op.add_window_assigner(window)
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(10_000)
        out = []
        for lo in range(0, 120, 30):
            op.process_elements(vals[lo:lo + 30].astype(np.float32),
                                jig[lo:lo + 30])
            wm = int(ts[min(lo + 29, 119)])
            out += [(w.start, w.end, round(float(w.agg_values[0]), 3)
                     if w.has_value() else None)
                    for w in op.process_watermark(wm)]
        op.check_overflow()
        return out

    tuned = drive(SessionWindow(Time, 20))
    generic = drive(CappedSessionWindow(Time, 20, 1 << 40))
    assert tuned == generic, (tuned[:5], generic[:5])


def test_hybrid_routes_context_windows():
    """Hybrid: device when the window has a device face, host otherwise."""
    from scotty_tpu.core.windows import ForwardContextAware, WindowContext
    from scotty_tpu.hybrid import HybridWindowOperator

    class HostOnlyContextWindow(ForwardContextAware):
        measure = Time

        def create_context(self):
            return WindowContext()

    dev = HybridWindowOperator(engine_config=SMALL)
    dev.add_window_assigner(CappedSessionWindow(Time, 10, 30))
    dev.add_aggregation(SumAggregation())
    dev.process_element(1.0, 5)
    assert dev.backend == "device"

    host = HybridWindowOperator(engine_config=SMALL)
    host.add_window_assigner(HostOnlyContextWindow())
    host.add_aggregation(SumAggregation())
    host._resolve()
    assert host.backend == "host"


def test_count_measure_context_window_routes_to_host():
    """ADVICE r4 (medium): the device context calculus runs over event
    TIMESTAMPS; count-measure context windows (whose host face — and the
    reference, TupleContext.getTs(measure) — runs over arrival positions)
    must fall back to the host, never silently reach the device."""
    from scotty_tpu.engine.operator import UnsupportedOnDevice
    from scotty_tpu.hybrid import HybridWindowOperator

    Count = WindowMeasure.Count
    w = CappedSessionWindow(Count, 3, 10)
    assert w.device_context_spec() is not None  # spec exists, measure gates

    dev = TpuWindowOperator(config=SMALL)
    with pytest.raises(UnsupportedOnDevice):
        dev.add_window_assigner(w)

    hyb = HybridWindowOperator(engine_config=SMALL)
    hyb.add_window_assigner(CappedSessionWindow(Count, 3, 10))
    hyb.add_aggregation(SumAggregation())
    hyb._resolve()
    assert hyb.backend == "host"


def test_ctx_clear_delay_extends_orphan_retention():
    """ADVICE r4 (low): DeviceContextSpec.clear_delay() participates in
    the sweep's GC bound — retention beyond orphan_reach() is applied as
    slack, so a decider declaring a long clear_delay keeps its orphans
    past wm - max_lateness."""
    op = TpuWindowOperator(config=SMALL)
    op.add_window_assigner(CappedSessionWindow(Time, 10, 30))
    op.add_aggregation(SumAggregation())
    # CappedSessionDecider.clear_delay() = gap + max_span = 40,
    # orphan_reach() = gap = 10 → slack 30
    op.process_element(1.0, 5)
    op.process_watermark(4)            # force build
    assert op._ctx_gc_slack == (30,)


def test_capped_continuous_stream_bounded_active_rows():
    """The bench shape that exposed the first-reach degeneracy: a dense
    paced stream past the cap must keep splitting into successive capped
    sessions (bounded active rows), not insert one point window per
    tuple. Pinned against the exact oracle."""
    import jax

    gap, cap = 10, 40
    rng = np.random.default_rng(7)
    eng = TpuWindowOperator(config=SMALL)
    eng.add_window_assigner(CappedSessionWindow(Time, gap, cap))
    eng.add_aggregation(SumAggregation())
    eng.set_max_lateness(100)
    oracle = _ExactCapped(gap, cap)
    got, exp = [], []
    for i in range(6):
        ts = np.sort(rng.integers(i * 100, (i + 1) * 100,
                                  size=300)).astype(np.int64)
        vals = rng.random(300).astype(np.float32)
        for v, t in zip(vals, ts):
            oracle.add(float(v), int(t))
        eng.process_elements(vals.tolist(), ts.tolist())
        got += [(w.start, w.end, round(float(w.agg_values[0]), 2))
                for w in eng.process_watermark((i + 1) * 100)]
        exp += [(ws, we, round(sum(vs), 2)) for ws, we, vs in
                oracle.sweep((i + 1) * 100)]
        n = int(jax.device_get(eng._ctx_states[0].n))
        assert n <= 8, f"active rows exploded: {n}"
    eng.check_overflow()
    assert len(got) == len(exp) and len(got) >= 8
    for (gs, ge, gv), (es, ee, ev) in zip(sorted(got), sorted(exp)):
        assert (gs, ge) == (es, ee)
        assert abs(gv - ev) <= 1e-2 * max(1.0, abs(ev))


def test_chunk_kernel_equals_scan_kernel():
    """The certified in-order chain kernel must produce bit-equal active
    arrays to the per-tuple scan on the same sorted chunk (the
    inorder_chain_params contract)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from scotty_tpu.engine import context as ectx
    from scotty_tpu.engine import sessions as es

    gap, cap = 12, 50
    spec = ectx.CappedSessionDecider(gap, cap)
    aggs = (SumAggregation().device_spec(), MaxAggregation().device_spec())
    S, B = 128, 256
    scan_k = ectx.build_context_apply(aggs, spec, S)
    chunk_k = ectx.build_context_chunk(aggs, spec, S, B)

    rng = np.random.default_rng(21)
    # clustered sorted stream: bursts + gaps so the chain breaks on both
    # the gap rule and the span cap
    ts = np.cumsum(rng.choice([1, 2, 3, 30], size=B,
                              p=[0.5, 0.3, 0.15, 0.05])).astype(np.int64)
    vals = rng.random(B).astype(np.float32)
    m = np.ones((B,), bool)

    s0 = es.init_session_state(aggs, S, orphan_capacity=64)
    a = scan_k(s0, jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(m))
    s1 = es.init_session_state(aggs, S, orphan_capacity=64)
    b = chunk_k(s1, jnp.asarray(ts), jnp.asarray(vals), jnp.asarray(m))
    an, bn = int(a.n), int(b.n)
    assert an == bn and an > 3
    np.testing.assert_array_equal(np.asarray(a.first[:an]),
                                  np.asarray(b.first[:bn]))
    np.testing.assert_array_equal(np.asarray(a.last[:an]),
                                  np.asarray(b.last[:bn]))
    np.testing.assert_array_equal(np.asarray(a.counts[:an]),
                                  np.asarray(b.counts[:bn]))
    for pa, pb in zip(a.partials, b.partials):
        # sum partials: prefix-diff vs sequential adds — f32
        # accumulation-order noise only
        np.testing.assert_allclose(np.asarray(pa[:an]),
                                   np.asarray(pb[:bn]), rtol=1e-4,
                                   atol=1e-4)
    assert not bool(a.overflow) and not bool(b.overflow)


def test_chunk_kernel_small_capacity_no_spurious_overflow():
    """r5 review: the chunk kernel's append block must not shrink usable
    capacity (capacity < max_segments ran fine on the scan kernel and
    must keep running on the chunk kernel)."""
    import jax

    op = TpuWindowOperator(config=EngineConfig(
        capacity=48, batch_size=64, annex_capacity=64, min_trigger_pad=32))
    op.add_window_assigner(CappedSessionWindow(Time, 10, 40))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(100)
    rng = np.random.default_rng(0)
    for i in range(6):
        ts = np.sort(rng.integers(i * 100, (i + 1) * 100,
                                  size=64)).astype(np.int64)
        op.process_elements(rng.random(64).astype(np.float32).tolist(),
                            ts.tolist())
        op.process_watermark((i + 1) * 100)
    op.check_overflow()
    assert int(jax.device_get(op._ctx_states[0].n)) <= 4
