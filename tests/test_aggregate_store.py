"""Store lookup/insert tests — transliterated from
slicing/src/test/.../LazyAggregateStoreTest.java."""

import pytest

from scotty_tpu.core import ReduceAggregateFunction
from scotty_tpu.simulator import (
    Fixed,
    LazyAggregateStore,
    SliceFactory,
    WindowManager,
)
from scotty_tpu.state import MemoryStateFactory


@pytest.fixture
def env():
    store = LazyAggregateStore()
    state_factory = MemoryStateFactory()
    window_manager = WindowManager(state_factory, store)
    slice_factory = SliceFactory(window_manager, state_factory)
    window_manager.add_aggregation(ReduceAggregateFunction(lambda a, b: a + b))
    return store, slice_factory


def _fill(store, sf, bounds=((0, 10), (10, 20), (20, 30), (40, 50))):
    slices = [sf.create_slice_now(a, b, Fixed()) for a, b in bounds]
    for s in slices:
        store.append_slice(s)
    return slices


def test_get_slice_by_index(env):
    store, sf = env
    slices = _fill(store, sf)
    for i, s in enumerate(slices):
        assert store.get_slice(i) is s
    assert store.get_current_slice() is slices[-1]


def test_find_slice_by_ts(env):
    store, sf = env
    slices = _fill(store, sf)
    for i, s in enumerate(slices):
        assert store.find_slice_index_by_timestamp(s.t_start) == i
        assert store.find_slice_index_by_timestamp(s.t_end - 1) == i
        assert store.find_slice_index_by_timestamp(s.t_start + 5) == i


def test_insert_value(env):
    store, sf = env
    _fill(store, sf)

    store.insert_value_to_slice(1, 1, 14)
    store.insert_value_to_slice(2, 2, 22)
    store.insert_value_to_current_slice(3, 22)

    assert store.get_slice(0).agg_state.get_values() == []
    assert store.get_slice(1).agg_state.get_values()[0] == 1
    assert store.get_slice(2).agg_state.get_values()[0] == 2
    assert store.get_slice(3).agg_state.get_values()[0] == 3


def test_pluggable_store_factory_seam():
    """The AggregationStore seam (aggregationstore/AggregationStore.java:7-87
    + AggregationStoreFactory.java:3-6): a custom store plugs into the
    operator through the factory and produces identical results."""
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.simulator import SlicingWindowOperator
    from scotty_tpu.simulator.operator import (
        AggregationStore,
        AggregationStoreFactory,
        LazyAggregateStore,
    )

    calls = {"aggregate": 0, "append": 0}

    class SpyStore(LazyAggregateStore):
        def aggregate(self, *a, **k):
            calls["aggregate"] += 1
            return super().aggregate(*a, **k)

        def append_slice(self, s):
            calls["append"] += 1
            return super().append_slice(s)

    class SpyFactory(AggregationStoreFactory):
        def create_aggregation_store(self):
            return SpyStore()

    def drive(op):
        op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 10))
        op.add_aggregation(SumAggregation())
        for v, t in [(1, 1), (2, 12), (3, 15), (4, 27)]:
            op.process_element(v, t)
        return [(w.get_start(), w.get_end(), w.get_agg_values()[0])
                for w in op.process_watermark(30) if w.has_value()]

    plugged = drive(SlicingWindowOperator(store_factory=SpyFactory()))
    default = drive(SlicingWindowOperator())
    assert plugged == default == [(0, 10, 1), (10, 20, 5), (20, 30, 4)]
    assert calls["aggregate"] >= 1 and calls["append"] >= 1
    assert isinstance(SpyStore(), AggregationStore)
