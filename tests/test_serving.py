"""Dynamic multi-query serving (ISSUE 6): differential churn suite,
zero-retrace/recycling property tests, admission + cache + checkpoint
coverage, and the operator/connector control paths.

The central oracle (test_churn_bitmatch_superset_oracle): the aligned
engine's state evolution is INDEPENDENT of the registered query set and
every trigger row's range query is independent of every other, so a
serving run under an arbitrary register/cancel schedule must produce,
for each query active at interval i, EXACTLY the bytes an always-active
superset run produces for that query at interval i. Any mask, slot
write, recycling, or bucketing bug breaks bit-equality.
"""

import numpy as np
import pytest

from scotty_tpu import obs as _obs
from scotty_tpu.core.aggregates import SumAggregation
from scotty_tpu.core.windows import (
    SessionWindow,
    SlidingWindow,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.engine.pipeline import (
    AlignedStreamPipeline,
    SlotGeometry,
    build_slot_trigger_grid,
    build_trigger_grid,
    init_query_slots,
)
from scotty_tpu.serving import (
    GeometryCache,
    QueryAdmission,
    QueryRejected,
    QueryService,
    ServingUnsupported,
    pad_pow2,
    replay_schedule,
)

Time = WindowMeasure.Time

SMALL = EngineConfig(capacity=1 << 12, annex_capacity=8, min_trigger_pad=32)


def make_service(windows=(), max_queries=64, quota=0, on_reject="fail",
                 cache_capacity=8, obs=None, seed=7, throughput=10_000,
                 min_slots=8):
    return QueryService(
        [SumAggregation()], slice_grid=100, max_window_size=4000,
        throughput=throughput, wm_period_ms=1000, max_lateness=1000,
        seed=seed, config=SMALL,
        admission=QueryAdmission(max_queries=max_queries,
                                 per_tenant_quota=quota,
                                 on_reject=on_reject),
        windows=list(windows), min_slots=min_slots,
        cache_capacity=cache_capacity, obs=obs)


def rows_of(by_slot, slot):
    return [(s, e, c, tuple(np.float32(v).tobytes() for v in vals))
            for (s, e, c, vals) in by_slot.get(slot, ())]


# ---------------------------------------------------------------------------
# the masked trigger grid itself
# ---------------------------------------------------------------------------


def test_slot_trigger_grid_matches_static_builder():
    """Per window, the masked [Q, K] grid's valid trigger rows equal the
    static builder's — same (start, end) sets at several watermarks,
    including the first-watermark clamp and the sliding end<=wm+1 quirk."""
    import jax

    windows = [TumblingWindow(Time, 500), SlidingWindow(Time, 4000, 1000),
               SlidingWindow(Time, 1500, 500)]
    P = 1000
    static_mk, _ = build_trigger_grid(windows, P)
    geom = SlotGeometry(n_slots=4, triggers_per_slot=8, slice_grid=100,
                        max_size=4000)
    slot_mk, T = build_slot_trigger_grid(geom, P)
    assert T == 32
    rows = {"kinds": np.zeros(4, np.int32), "grids": np.ones(4, np.int64),
            "sizes": np.ones(4, np.int64), "active": np.zeros(4, bool)}
    from scotty_tpu.serving import window_row

    for q, w in enumerate(windows):
        k, g, s = window_row(w, 100, 4000)
        rows["kinds"][q], rows["grids"][q], rows["sizes"][q] = k, g, s
        rows["active"][q] = True
    qs = init_query_slots(geom, rows)
    for (last_wm, wm) in ((0, 1000), (1000, 2000), (7000, 8000)):
        sws, swe, sok = jax.device_get(
            static_mk(np.int64(last_wm), np.int64(wm)))
        mws, mwe, mok = jax.device_get(
            slot_mk(qs, np.int64(last_wm), np.int64(wm)))
        static_rows = sorted(zip(sws[sok].tolist(), swe[sok].tolist()))
        masked = sorted(zip(mws[mok].tolist(), mwe[mok].tolist()))
        assert masked == static_rows, (last_wm, wm)
        # slot 3 is inactive: none of its lanes may be valid
        assert not mok[3 * 8:].any()


def test_masked_frozen_set_matches_static_pipeline_bitexact():
    """A serving pipeline with a frozen query set emits the exact bytes
    of a static pipeline whose window set implies the same slice grid
    (same geometry => same generated stream => same state => the same
    per-row range queries)."""
    windows = [SlidingWindow(Time, 400, 100), TumblingWindow(Time, 200)]
    static = AlignedStreamPipeline(
        windows, [SumAggregation()], config=SMALL, throughput=10_000,
        wm_period_ms=1000, max_lateness=1000, seed=3)
    assert static.grid == 100
    svc = make_service(windows, seed=3)
    souts = static.run(4)
    static.sync()
    vouts = svc.run(4)
    svc.sync()
    for so, vo in zip(souts, vouts):
        srows = sorted((s, e, c, tuple(np.float32(v).tobytes()
                                       for v in vals))
                       for (s, e, c, vals) in static.lowered_results(so))
        vrows = sorted((s, e, c, tuple(np.float32(v).tobytes()
                                       for v in vals))
                       for (s, e, c, vals) in svc.lowered_results(vo))
        assert srows == vrows
    static.check_overflow()
    svc.check_overflow()


# ---------------------------------------------------------------------------
# zero-retrace + recycling properties
# ---------------------------------------------------------------------------


def test_cancel_reregister_same_bucket_zero_retraces_and_recycles():
    svc = make_service([SlidingWindow(Time, 4000, 1000)])
    h = svc.register(TumblingWindow(Time, 500), tenant="alice")
    svc.run(2, collect=False)
    svc.sync()
    svc.mark_warm()
    first_slot = h.slot
    for i in range(6):
        svc.cancel(h)
        h = svc.register(TumblingWindow(Time, 1000) if i % 2
                         else TumblingWindow(Time, 500), tenant="alice")
        # LIFO free-list: the freed slot is recycled immediately
        assert h.slot == first_slot
        svc.run(1, collect=False)
    svc.sync()
    svc.check_overflow()
    assert svc.retraces_since_warm == 0
    assert svc.stats().get("serving_retraces", 0) == 0


def test_stale_handle_cancel_raises():
    svc = make_service()
    h = svc.register(TumblingWindow(Time, 500))
    svc.cancel(h)
    with pytest.raises(ValueError, match="stale or unknown"):
        svc.cancel(h)
    h2 = svc.register(TumblingWindow(Time, 500))
    assert h2.slot == h.slot and h2.gen == h.gen + 1
    with pytest.raises(ValueError, match="stale or unknown"):
        svc.cancel(h)          # recycled slot, old generation


def test_serving_unsupported_windows_raise():
    svc = make_service()
    with pytest.raises(ServingUnsupported, match="no dynamic-serving"):
        svc.register(SessionWindow(Time, 1000))
    with pytest.raises(ServingUnsupported, match="slice grid"):
        svc.register(TumblingWindow(Time, 250))      # off the 100ms grid
    with pytest.raises(ServingUnsupported, match="retention"):
        svc.register(TumblingWindow(Time, 400000))   # beyond max_size
    with pytest.raises(ServingUnsupported, match="count-measure"):
        svc.register(TumblingWindow(WindowMeasure.Count, 100))


# ---------------------------------------------------------------------------
# the differential churn suite (superset oracle, bit-exact)
# ---------------------------------------------------------------------------


def test_churn_bitmatch_superset_oracle():
    rng = np.random.default_rng(11)
    pool = [TumblingWindow(Time, 500), TumblingWindow(Time, 1000),
            SlidingWindow(Time, 2000, 500), SlidingWindow(Time, 4000, 1000),
            SlidingWindow(Time, 1000, 200)]
    # seeded schedule: 40 ops over 8 intervals, max ~6 live
    schedule = [[] for _ in range(8)]
    live, next_id = [], 0
    for i in range(8):
        for _ in range(5):
            if live and (len(live) >= 6 or rng.random() < 0.45):
                rid = live.pop(int(rng.integers(len(live))))
                schedule[i].append(("cancel", rid))
            else:
                w = pool[int(rng.integers(len(pool)))]
                schedule[i].append(
                    ("register", next_id, w, f"t{next_id % 3}"))
                live.append(next_id)
                next_id += 1

    svc = make_service([SlidingWindow(Time, 4000, 1000)], seed=5)
    svc.run(6, collect=False)        # warmup past the widest span
    svc.sync()
    svc.mark_warm()
    handles, slot_maps, outs = {}, [], []
    for cmds in schedule:
        replay_schedule(svc, cmds, handles)
        slot_maps.append({rid: h.slot for rid, h in handles.items()})
        outs.extend(svc.run(1))
    svc.sync()
    svc.check_overflow()
    assert svc.retraces_since_warm == 0

    # superset oracle: same seed/geometry, every registration active from
    # the start, generous slots
    oracle = make_service([SlidingWindow(Time, 4000, 1000)], seed=5,
                          max_queries=next_id + 4, min_slots=8)
    ohandles = {}
    for cmds in schedule:
        for cmd in cmds:
            if cmd[0] == "register":
                ohandles[cmd[1]] = oracle.register(cmd[2], tenant=cmd[3])
    oracle.run(6, collect=False)
    oracle.sync()
    oouts = oracle.run(8)
    oracle.sync()
    oracle.check_overflow()

    compared = 0
    for i, omap in enumerate(slot_maps):
        srows = svc.results_by_slot(outs[i])
        orows = oracle.results_by_slot(oouts[i])
        for rid, slot in omap.items():
            assert rows_of(srows, slot) == rows_of(
                orows, ohandles[rid].slot), (i, rid)
            compared += len(rows_of(srows, slot))
    assert compared > 20            # the comparison actually saw emissions


def test_register_mid_stream_sees_preexisting_slices():
    """The shared-slice claim: a query registered at interval r answers
    windows over data ingested BEFORE r (no per-query state to backfill)."""
    svc = make_service([TumblingWindow(Time, 500)], seed=9)
    svc.run(3, collect=False)
    svc.sync()
    h = svc.register(SlidingWindow(Time, 4000, 1000))
    out = svc.run(1)[0]
    svc.sync()
    rows = svc.results_by_slot(out).get(h.slot)
    assert rows, "freshly registered window emitted nothing"
    (s, e, c, vals) = rows[0]
    # the window spans 4 s — intervals 0..3's tuples, all pre-registration
    assert e - s == 4000 and c == 4 * svc.pipeline.tuples_per_interval
    svc.check_overflow()


# ---------------------------------------------------------------------------
# admission + tenancy
# ---------------------------------------------------------------------------


def test_admission_quota_and_capacity():
    obs = _obs.Observability(flight=_obs.FlightRecorder(128))
    svc = make_service(max_queries=4, quota=2, obs=obs)
    a1 = svc.register(TumblingWindow(Time, 500), tenant="alice")
    svc.register(TumblingWindow(Time, 1000), tenant="alice")
    with pytest.raises(QueryRejected) as ei:
        svc.register(TumblingWindow(Time, 2000), tenant="alice")
    assert ei.value.reason == "quota"
    svc.register(TumblingWindow(Time, 500), tenant="bob")
    svc.register(TumblingWindow(Time, 500), tenant="carol")
    with pytest.raises(QueryRejected) as ei:
        svc.register(TumblingWindow(Time, 500), tenant="dave")
    assert ei.value.reason == "capacity"
    assert svc.stats()["serving_rejected"] == 2
    snap = obs.snapshot()
    assert snap["serving_rejected"] == 2
    assert snap["serving_tenant_active_alice"] == 2
    kinds = {e["kind"] for e in obs.flight.events()}
    assert {"query_register", "query_reject"} <= kinds
    # cancelling frees quota again
    svc.cancel(a1)
    assert svc.register(TumblingWindow(Time, 500), tenant="alice")


def test_admission_shed_policy_counts_and_calls_back():
    shed = []
    adm = QueryAdmission(max_queries=1, on_reject="shed",
                         reject_callback=lambda w, t, r: shed.append((t, r)))
    svc = QueryService(
        [SumAggregation()], slice_grid=100, max_window_size=4000,
        throughput=10_000, wm_period_ms=1000, max_lateness=1000, seed=7,
        config=SMALL, admission=adm)
    assert svc.register(TumblingWindow(Time, 500)) is not None
    assert svc.register(TumblingWindow(Time, 1000), tenant="t2") is None
    assert shed == [("t2", "capacity")]
    assert svc.stats()["serving_rejected"] == 1


# ---------------------------------------------------------------------------
# geometry-bucketed compile cache
# ---------------------------------------------------------------------------


def test_rebucket_miss_hit_and_compact_back_to_warm_bucket():
    svc = make_service([SlidingWindow(Time, 4000, 1000)], max_queries=256)
    svc.run(2, collect=False)
    svc.sync()
    svc.mark_warm()
    g0 = svc.geometry
    # a finer-slide window outgrows the lane bucket: miss + retrace
    h = svc.register(SlidingWindow(Time, 1000, 100))
    assert svc.geometry.triggers_per_slot > g0.triggers_per_slot
    svc.run(1, collect=False)
    svc.sync()
    assert svc.retraces_since_warm == 1
    st = svc.stats()
    assert st["serving_cache_misses"] == 1 and st["serving_retraces"] == 1
    # cancel it and compact: back onto the ORIGINAL bucket — a cache hit,
    # no new trace
    svc.cancel(h)
    assert svc.compact() is True
    assert svc.geometry == g0
    svc.run(1, collect=False)
    svc.sync()
    assert svc.retraces_since_warm == 1          # unchanged: warm swap
    assert svc.stats()["serving_cache_misses"] == 1
    svc.check_overflow()


def test_slot_growth_rebuckets_and_lru_evicts():
    obs = _obs.Observability(flight=_obs.FlightRecorder(256))
    svc = make_service(max_queries=64, cache_capacity=1, obs=obs,
                       min_slots=2)
    svc.run(1, collect=False)
    svc.sync()
    handles = [svc.register(TumblingWindow(Time, 500)) for _ in range(2)]
    # third register outgrows the 2-slot pad: rebucket to 4 slots; with
    # cache_capacity=1 the original bucket is evicted
    handles.append(svc.register(TumblingWindow(Time, 500)))
    assert svc.geometry.n_slots == 4
    st = svc.stats()
    assert st["serving_cache_misses"] == 1
    assert st["serving_cache_evictions"] == 1
    assert "query_evict" in {e["kind"] for e in obs.flight.events()}
    svc.run(1, collect=False)
    svc.sync()
    svc.check_overflow()


def test_compact_then_grow_keeps_stale_handles_dead():
    """Review finding: compact() used to truncate generation counters, so
    a later grow reset them to 0 and a pre-compact stale handle could
    cancel another tenant's live query in the recycled slot."""
    svc = make_service(max_queries=64, min_slots=2)
    hs = [svc.register(TumblingWindow(Time, 500), tenant="alice")
          for _ in range(3)]                  # grows past min_slots
    high = max(hs, key=lambda h: h.slot)
    for h in hs:
        svc.cancel(h)
    assert svc.compact() is True              # drops the high slots
    # regrow: a new tenant's query lands in the recycled high slot
    regs = []
    while True:
        h = svc.register(TumblingWindow(Time, 500), tenant="bob")
        regs.append(h)
        if h.slot == high.slot:
            break
    with pytest.raises(ValueError, match="stale or unknown"):
        svc.cancel(high)                      # stale pre-compact handle
    assert svc.table.tenant_active("bob") == len(regs)


def test_tenant_gauge_zeroes_after_last_cancel():
    """Review finding: a tenant whose last query was cancelled kept its
    final nonzero serving_tenant_active_<t> gauge forever."""
    obs = _obs.Observability()
    svc = make_service(obs=obs)
    h1 = svc.register(TumblingWindow(Time, 500), tenant="alice")
    h2 = svc.register(TumblingWindow(Time, 1000), tenant="alice")
    assert obs.snapshot()["serving_tenant_active_alice"] == 2
    svc.cancel(h1)
    svc.cancel(h2)
    assert obs.snapshot()["serving_tenant_active_alice"] == 0


def test_tenant_gauge_cardinality_capped_to_topk_with_rollup():
    """ISSUE 13 satellite: serving_tenant_active_<t> minted one gauge
    per tenant name forever — at mesh-service tenant counts that bloats
    /metrics and obs diff inputs. Only the top-k tenants by active
    count keep named gauges; the rest fold into serving_tenant_other;
    displaced tenants are zeroed, not left stuck."""
    import scotty_tpu.serving.service as _svc_mod

    obs = _obs.Observability()
    svc = make_service(obs=obs)
    svc.tenant_gauge_top_k = 2
    handles = {}
    for t, n in (("alice", 3), ("bob", 2), ("carol", 1), ("dave", 1)):
        handles[t] = [svc.register(TumblingWindow(Time, 500), tenant=t)
                      for _ in range(n)]
    snap = obs.snapshot()
    assert snap["serving_tenant_active_alice"] == 3
    assert snap["serving_tenant_active_bob"] == 2
    assert snap["serving_tenant_other"] == 2          # carol + dave
    assert "serving_tenant_active_carol" not in snap
    # alice cancels down to 0: she leaves the named set AND reads 0
    # (the gauge-zeroing-on-last-cancel behavior survives the rollup)
    for h in handles["alice"]:
        svc.cancel(h)
    snap = obs.snapshot()
    assert snap["serving_tenant_active_alice"] == 0
    assert snap["serving_tenant_active_bob"] == 2
    # ties at 1 break by name: carol gets the second named gauge
    assert snap["serving_tenant_active_carol"] == 1
    assert snap["serving_tenant_other"] == 1          # dave
    # every tenant named by the rollup resolves through the shared
    # helper — the helper is the one place both serving layers emit from
    assert _svc_mod.emit_tenant_gauges is not None


def test_replay_schedule_tolerates_shed_registers():
    """Review finding: a cancel whose matching register was shed by
    admission used to KeyError mid-schedule."""
    svc = QueryService(
        [SumAggregation()], slice_grid=100, max_window_size=4000,
        throughput=10_000, wm_period_ms=1000, max_lateness=1000, seed=7,
        config=SMALL,
        admission=QueryAdmission(max_queries=1, on_reject="shed"))
    schedule = [
        ("register", 0, TumblingWindow(Time, 500), "a"),
        ("register", 1, TumblingWindow(Time, 1000), "b"),   # shed
        ("cancel", 1),                                      # no-op
        ("cancel", 0),
    ]
    handles = replay_schedule(svc, schedule)
    assert handles == {}
    assert svc.table.n_active == 0
    assert svc.stats()["serving_rejected"] == 1


def test_pad_pow2_and_cache_lru_unit():
    assert pad_pow2(0, 8) == 8
    assert pad_pow2(8, 8) == 8
    assert pad_pow2(9, 8) == 16
    assert pad_pow2(1000, 8) == 1024
    with pytest.raises(ValueError):
        pad_pow2(-1, 8)
    c = GeometryCache(2)
    c.put("a", 1), c.put("b", 2)
    assert c.get("a") == 1                 # refreshes LRU order
    assert c.put("c", 3) == "b"            # b was least-recent
    assert c.get("b") is None
    assert c.stats()["evictions"] == 1


def test_trigger_budget_checked_against_max_triggers():
    with pytest.raises(ValueError, match="max_triggers"):
        QueryService(
            [SumAggregation()], slice_grid=100, max_window_size=4000,
            throughput=10_000, wm_period_ms=1000, seed=1,
            config=EngineConfig(capacity=1 << 12, annex_capacity=8,
                                min_trigger_pad=32, max_triggers=64),
            min_slots=64, min_trigger_lanes=8)


# ---------------------------------------------------------------------------
# checkpoint/restore: the query table rides the snapshot
# ---------------------------------------------------------------------------


def test_checkpoint_restore_replays_active_set(tmp_path):
    path = str(tmp_path / "ckpt")
    svc = make_service([SlidingWindow(Time, 4000, 1000)], seed=13)
    h1 = svc.register(TumblingWindow(Time, 500), tenant="alice")
    h2 = svc.register(TumblingWindow(Time, 1000), tenant="bob")
    svc.run(5, collect=False)
    svc.sync()
    svc.cancel(h1)                       # free-list state matters too
    svc.run(1, collect=False)
    svc.sync()
    svc.save(path)
    cont = [svc.results_by_slot(o) for o in svc.run(3)]
    svc.sync()

    svc2 = make_service([SlidingWindow(Time, 4000, 1000)], seed=13)
    svc2.restore(path)
    rest = [svc2.results_by_slot(o) for o in svc2.run(3)]
    svc2.sync()
    assert len(cont) == len(rest)
    for a, b in zip(cont, rest):
        assert {k: rows_of(a, k) for k in a} == {k: rows_of(b, k)
                                                for k in b}
    # table bookkeeping restored exactly: the cancelled slot is the next
    # one recycled, stale handles still rejected
    h3 = svc2.register(TumblingWindow(Time, 2000), tenant="carol")
    assert h3.slot == h1.slot and h3.gen == h1.gen + 1
    with pytest.raises(ValueError):
        svc2.cancel(QueryHandleLike(h2))
    svc2.check_overflow()


class QueryHandleLike:
    """A stale copy of a handle whose generation has moved on."""

    def __init__(self, h):
        self.slot, self.gen = h.slot, h.gen - 1
        self.kind, self.grid, self.size, self.tenant = (h.kind, h.grid,
                                                        h.size, h.tenant)


def test_restore_refuses_wrong_grid(tmp_path):
    path = str(tmp_path / "ckpt")
    svc = make_service([SlidingWindow(Time, 4000, 1000)])
    svc.run(2, collect=False)
    svc.sync()
    svc.save(path)
    other = QueryService(
        [SumAggregation()], slice_grid=200, max_window_size=4000,
        throughput=10_000, wm_period_ms=1000, seed=7, config=SMALL)
    with pytest.raises(ValueError, match="slice grid"):
        other.restore(path)


# ---------------------------------------------------------------------------
# operator + connector control paths
# ---------------------------------------------------------------------------


def run_operator_churn(op, sim, stream, watermarks, commands):
    """Drive device operator + simulator through the same stream with the
    same register/cancel points; compare per-watermark emissions (the
    engine-vs-simulator discipline of test_engine_differential, plus
    serving control commands keyed on tuple position)."""
    from tests.test_engine_differential import compare

    cmd_at = {}
    for (after_idx, fn) in commands:
        cmd_at.setdefault(after_idx, []).append(fn)
    pos = 0
    for after_idx, wm in watermarks:
        while pos <= after_idx and pos < len(stream):
            for fn in cmd_at.get(pos, ()):
                fn()
            v, ts = stream[pos]
            sim.process_element(v, ts)
            op.process_element(v, ts)
            pos += 1
        compare(sim.process_watermark(wm), op.process_watermark(wm), wm)


def test_operator_register_cancel_matches_simulator_zero_rebuild():
    from scotty_tpu.simulator import SlicingWindowOperator

    sim = SlicingWindowOperator()
    op = TpuWindowOperator(config=SMALL)
    for o in (sim, op):
        o.add_window_assigner(TumblingWindow(Time, 10))
        o.add_aggregation(SumAggregation())
        o.set_max_lateness(1000)
    stream = [(i % 7 + 1, i * 3) for i in range(60)]
    holders = {}

    def reg():
        # compatible: 20 is a multiple of the registered period 10 —
        # zero kernel rebuild on the device operator
        w = TumblingWindow(Time, 20)
        holders["op"] = op.register_window(w)
        holders["sim"] = sim.register_window(w)

    def cancel():
        op.cancel_window(holders["op"])
        sim.cancel_window(holders["sim"])

    # force the build with the first watermark region, then register
    run_operator_churn(op, sim, stream, [(9, 30)], [])
    ingest_before = op._ingest
    query_before = op._query
    run_operator_churn(op, sim, stream,
                       [(19, 60), (29, 90), (39, 120), (59, 181)],
                       [(12, reg), (32, cancel)])
    assert op._ingest is ingest_before          # no kernel rebuild
    assert op._query is query_before


def test_operator_incompatible_register_rebuilds_and_counts():
    """A window whose edges miss the built union grid cannot be served by
    masking — register_window falls back to the kernel-rebuild path
    (counted as a serving retrace). Early windows straddling the addition
    follow the documented `_add_window_dynamic` deviation, so this test
    asserts the rebuild + accounting + that the new window emits, not a
    simulator bit-match."""
    obs = _obs.Observability()
    op = TpuWindowOperator(config=SMALL, obs=obs)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    stream = [(i + 1, i * 4) for i in range(40)]
    for v, ts in stream[:13]:
        op.process_element(v, ts)
    op.process_watermark(30)
    ingest_before = op._ingest
    h = op.register_window(TumblingWindow(Time, 15))   # 15 % 10 != 0
    assert op._ingest is not ingest_before      # kernels were rebuilt
    for v, ts in stream[13:]:
        op.process_element(v, ts)
    out = op.process_watermark(161)
    assert [w for w in out if w.get_end() - w.get_start() == 15]
    op.cancel_window(h)
    out2 = op.process_watermark(200)
    assert not [w for w in out2 if w.get_end() - w.get_start() == 15]
    snap = obs.snapshot()
    assert snap["serving_registered"] == 1
    assert snap["serving_retraces"] == 1
    assert snap["serving_cancelled"] == 1


def test_operator_churn_recycles_window_slots():
    """Review finding: sustained operator-path churn must bound
    self.windows at peak concurrency (cancelled slots recycle), and
    stale handles must never touch a recycled slot."""
    op = TpuWindowOperator(config=SMALL)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    for i in range(5):
        op.process_element(i + 1, i * 3)
    op.process_watermark(12)                      # build
    n0 = len(op.windows)
    first = op.register_window(TumblingWindow(Time, 20))
    op.cancel_window(first)
    for k in range(20):
        h = op.register_window(TumblingWindow(Time, 20 if k % 2 else 40))
        op.cancel_window(h)
        assert h != first                         # handles never reused
    assert len(op.windows) == n0 + 1              # slot recycled, no growth
    with pytest.raises(ValueError, match="unknown or already-cancelled"):
        op.cancel_window(first)                   # stale handle stays dead


def test_connector_run_global_control_path():
    from scotty_tpu.connectors.base import (
        GlobalScottyWindowOperator,
        PeriodicWatermarks,
    )
    from scotty_tpu.connectors.iterable import run_global

    def results(control):
        op = GlobalScottyWindowOperator(
            windows=[TumblingWindow(Time, 100)],
            aggregations=[SumAggregation()],
            watermark_policy=PeriodicWatermarks(period=100),
            allowed_lateness=1)
        src = ((float(i), i * 10) for i in range(100))
        return [(w.get_start(), w.get_end(), tuple(w.get_agg_values()))
                for w in run_global(src, op, control=control)], op

    base, _ = results(None)
    hold = {}
    ctl = [
        (30, lambda op: hold.update(
            h=op.register_window(TumblingWindow(Time, 200)))),
        (70, lambda op: op.cancel_window(hold["h"])),
    ]
    churned, op = results(ctl)
    extra = [r for r in churned if r[1] - r[0] == 200]
    assert extra, "registered window never emitted"
    # it emitted only while active: ends within (300, 700]
    assert all(300 < e <= 701 for (_, e, _) in extra)
    base_set = [r for r in churned if r[1] - r[0] == 100]
    assert base_set == base                  # the static query unaffected


def test_connector_keyed_control_applies_to_new_keys():
    from scotty_tpu.connectors.base import (
        KeyedScottyWindowOperator,
        PeriodicWatermarks,
    )

    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 100)],
        aggregations=[SumAggregation()],
        watermark_policy=PeriodicWatermarks(period=100),
        allowed_lateness=1)
    out = []
    for i in range(30):                      # key "a" only
        out.extend(op.process_element("a", 1.0, i * 10))
    h = op.register_window(TumblingWindow(Time, 300))
    for i in range(30, 90):                  # key "b" appears later
        out.extend(op.process_element("a", 1.0, i * 10))
        out.extend(op.process_element("b", 2.0, i * 10))
    wide = [(k, w.get_start(), w.get_end()) for k, w in out
            if w.get_end() - w.get_start() == 300]
    assert {k for k, _, _ in wide} == {"a", "b"}
    op.cancel_window(h)
    out2 = []
    for i in range(90, 150):
        out2.extend(op.process_element("a", 1.0, i * 10))
        out2.extend(op.process_element("b", 2.0, i * 10))
    assert not [w for _, w in out2
                if w.get_end() - w.get_start() == 300]


# ---------------------------------------------------------------------------
# satellites: trigger_pad cap, diff gate, churn bench cell
# ---------------------------------------------------------------------------


def test_trigger_pad_raises_above_max_triggers():
    cfg = EngineConfig(min_trigger_pad=32, max_triggers=256)
    assert cfg.trigger_pad(10) == 32
    assert cfg.trigger_pad(200) == 256
    assert cfg.trigger_pad(256) == 256
    with pytest.raises(ValueError) as ei:
        cfg.trigger_pad(257)
    assert "max_triggers=256" in str(ei.value)
    assert "257" in str(ei.value)


def test_diff_gate_serving_thresholds(tmp_path):
    import json

    from scotty_tpu.obs.diff import diff_exports

    base = [{"name": "c", "windows": "w", "engine": "QueryChurn",
             "aggregation": "sum", "tuples_per_sec": 100.0,
             "metrics": {"metrics": {}}}]
    cand_bad = [dict(base[0], metrics={"metrics": {
        "serving_retraces": 3, "serving_rejected": 1}})]
    bp = tmp_path / "base.json"
    cp = tmp_path / "cand.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cand_bad))
    findings = diff_exports(str(bp), str(cp))
    bad = {f["metric"] for f in findings if f["status"] == "regressed"}
    assert {"serving_retraces", "serving_rejected"} <= bad
    cp.write_text(json.dumps(base))
    findings = diff_exports(str(bp), str(cp))
    assert not [f for f in findings if f["status"] == "regressed"]


@pytest.mark.slow
def test_query_churn_bench_cell():
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_query_churn_cell

    cfg = BenchmarkConfig(
        name="churn-test", throughput=100_000, runtime_s=5,
        watermark_period_ms=1000, capacity=1 << 12, max_lateness=1000,
        seed=42, churn_ops=50, churn_max_active=24, churn_tenants=3,
        churn_oracle=True)
    res = run_query_churn_cell(cfg, "Sliding(4000,1000)+Tumbling(1000)",
                               "sum")
    assert res.serving_retraces_after_warmup == 0
    assert res.oracle_match is True
    assert res.churn_ops >= 50
    assert res.serving_registered + res.serving_cancelled >= 50
    assert len(res.churn_schedule) == res.churn_ops
    assert res.throughput_static > 0
