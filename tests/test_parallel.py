"""Keyed + global operator tests on a virtual 8-device CPU mesh
(SURVEY.md §4e — the reference never tests multi-node; we do)."""

import numpy as np
import pytest

from scotty_tpu import (
    MaxAggregation,
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.parallel import (
    GlobalTpuWindowOperator,
    KeyedTpuWindowOperator,
    make_mesh,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 10, batch_size=32, annex_capacity=128,
                   min_trigger_pad=32)


def keyed_reference(n_keys, windows, agg_factories, keys, vals, ts, wm,
                    lateness=1000):
    """Oracle: one host simulator per key (the reference connector model)."""
    sims = {}
    for k in range(n_keys):
        op = SlicingWindowOperator()
        for w in windows:
            op.add_window_assigner(w)
        for mk in agg_factories:
            op.add_aggregation(mk())
        op.set_max_lateness(lateness)
        sims[k] = op
    for k, v, t in zip(keys, vals, ts):
        sims[int(k)].process_element(float(v), int(t))
    out = {}
    for k in range(n_keys):
        out[k] = [w for w in sims[k].process_watermark(wm) if w.has_value()]
    return out


def test_keyed_matches_per_key_simulators():
    rng = np.random.default_rng(11)
    n_keys = 4
    N = 400
    keys = rng.integers(0, n_keys, size=N)
    ts = np.sort(rng.integers(0, 300, size=N))
    vals = rng.integers(1, 50, size=N)
    windows = [TumblingWindow(Time, 20), SlidingWindow(Time, 50, 10)]

    op = KeyedTpuWindowOperator(n_keys=n_keys, config=CFG)
    for w in windows:
        op.add_window_assigner(w)
    op.add_aggregation(SumAggregation())
    op.add_aggregation(MaxAggregation())
    op.process_keyed_elements(keys, vals, ts)
    wm = int(ts[-1]) + 1
    got = op.process_watermark(wm)

    want = keyed_reference(n_keys, windows, [SumAggregation, MaxAggregation],
                           keys, vals, ts, wm)
    got_by_key: dict = {k: [] for k in range(n_keys)}
    for k, w in got:
        got_by_key[k].append(w)
    for k in range(n_keys):
        assert len(got_by_key[k]) == len(want[k]), (k, got_by_key[k], want[k])
        for a, b in zip(want[k], got_by_key[k]):
            assert a.get_start() == b.get_start()
            assert a.get_end() == b.get_end()
            for x, y in zip(a.get_agg_values(), b.get_agg_values()):
                assert float(x) == pytest.approx(float(y), rel=1e-5)


def test_keyed_on_mesh():
    mesh = make_mesh("keys")
    n_keys = 8 * 2                       # 2 key shards per device
    op = KeyedTpuWindowOperator(n_keys=n_keys, config=CFG, mesh=mesh)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())

    rng = np.random.default_rng(5)
    N = 256
    keys = rng.integers(0, n_keys, size=N)
    ts = np.sort(rng.integers(0, 100, size=N))
    vals = np.ones(N)
    op.process_keyed_elements(keys, vals, ts)
    got = op.process_watermark(101)
    # total count across all keys/windows == N (tumbling partitions time)
    total = sum(w.get_agg_values()[0] for _, w in got)
    assert total == pytest.approx(N)


def test_global_operator_matches_single_simulator():
    rng = np.random.default_rng(3)
    N = 300
    ts = np.sort(rng.integers(0, 200, size=N))
    vals = rng.integers(1, 30, size=N)

    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 25))
    sim.add_aggregation(SumAggregation())
    for v, t in zip(vals, ts):
        sim.process_element(int(v), int(t))
    wm = int(ts[-1]) + 1
    want = sim.process_watermark(wm)

    op = GlobalTpuWindowOperator(n_shards=8, config=CFG, mesh=make_mesh("shards"))
    op.add_window_assigner(TumblingWindow(Time, 25))
    op.add_aggregation(SumAggregation())
    op.process_elements(vals, ts)
    got = op.process_watermark(wm)

    assert len(want) == len(got)
    for a, b in zip(want, got):
        assert a.get_start() == b.get_start()
        assert a.get_end() == b.get_end()
        assert a.has_value() == b.has_value()
        if a.has_value():
            assert float(a.get_agg_values()[0]) == pytest.approx(
                float(b.get_agg_values()[0]), rel=1e-5)


def test_global_combine_is_one_fused_collective_program():
    """VERDICT r1 item 8: the cross-shard merge must be ONE jitted program
    whose combine is an in-executable collective (psum → all-reduce over the
    mesh axis), not an eager reduction over fetched per-shard results."""
    import jax
    import numpy as np

    op = GlobalTpuWindowOperator(n_shards=8, config=CFG,
                                 mesh=make_mesh("shards"))
    op.add_window_assigner(TumblingWindow(Time, 25))
    op.add_aggregation(SumAggregation())
    op.add_aggregation(MaxAggregation())
    op.process_elements(np.ones(64), np.arange(64, dtype=np.int64))
    op._flush()
    gq = op._build_global_query()

    Tp = 32
    ws = np.zeros((Tp,), np.int64)
    we = np.full((Tp,), 25, np.int64)
    mask = np.zeros((Tp,), bool)
    mask[0] = True
    low = jax.jit(gq).lower(op._state, ws, we, mask)
    # psum/pmax appear as all_reduce ops INSIDE the single lowered program
    # (the CPU backend then compiles them to collective custom-calls; on TPU
    # they become ICI all-reduces) — one fused executable, zero host-side
    # combines
    assert low.as_text().count("all_reduce") >= 2
    low.compile()                  # and it compiles to one executable

    cnt, merged = gq(op._state, ws, we, mask)
    assert int(np.asarray(cnt)[0]) == 25          # tuples ts 0..24
    assert float(np.asarray(merged[0])[0, 0]) == 25.0   # global sum
    assert float(np.asarray(merged[1])[0, 0]) == 1.0    # global max


def test_keyed_device_rounds_match_per_key_simulators():
    """ingest_device_round (the zero-copy [K, B] device-source path used by
    the keyed benchmark) must produce the same per-key windows as one host
    simulator per key."""
    import jax
    import jax.numpy as jnp

    K, B = 4, 32
    op = KeyedTpuWindowOperator(n_keys=K, config=CFG)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_window_assigner(SlidingWindow(Time, 40, 20))
    op.add_aggregation(SumAggregation())

    rng = np.random.default_rng(2)
    all_rows = {k: [] for k in range(K)}
    lo = 0
    for _ in range(4):
        ts = np.sort(rng.integers(lo, lo + 50, size=(K, B)),
                     axis=1).astype(np.int64)
        vals = rng.integers(1, 9, size=(K, B)).astype(np.float32)
        op.ingest_device_round(jax.device_put(jnp.asarray(ts)),
                               jax.device_put(jnp.asarray(vals)),
                               jax.device_put(np.ones((K, B), bool)),
                               lo, lo + 49)
        for k in range(K):
            all_rows[k].extend(zip(vals[k], ts[k]))
        lo += 50
    wm = lo + 100
    got = op.process_watermark(wm)

    want = {}
    for k in range(K):
        sim = SlicingWindowOperator()
        sim.add_window_assigner(TumblingWindow(Time, 10))
        sim.add_window_assigner(SlidingWindow(Time, 40, 20))
        sim.add_aggregation(SumAggregation())
        for v, t in all_rows[k]:
            sim.process_element(float(v), int(t))
        want[k] = [w for w in sim.process_watermark(wm) if w.has_value()]

    got_by_key = {k: [] for k in range(K)}
    for k, w in got:
        got_by_key[k].append(w)
    for k in range(K):
        assert len(got_by_key[k]) == len(want[k]), k
        for a, b in zip(want[k], got_by_key[k]):
            assert (a.get_start(), a.get_end()) == (b.get_start(),
                                                    b.get_end())
            assert float(a.get_agg_values()[0]) == pytest.approx(
                float(b.get_agg_values()[0]), rel=1e-5)


def test_keyed_bench_cell_smoke():
    """run_keyed_cell (device-generated keyed stream + async watermark)
    completes and emits windows."""
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_keyed_cell

    cfg = BenchmarkConfig(name="k", throughput=100_000, runtime_s=3,
                          batch_size=1 << 13, capacity=1024, n_keys=32,
                          watermark_period_ms=1000)
    r = run_keyed_cell(cfg, "Tumbling(1000)", "sum")
    assert r.n_windows_emitted > 0
    assert r.tuples_per_sec > 0


def test_keyed_aligned_pipeline_on_mesh():
    """The fused keyed pipeline sharded over an 8-device mesh produces the
    same per-key results as unsharded (the program is per-key pointwise —
    SURVEY.md §2.8 (b); XLA partitions it collective-free)."""
    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    K = 8
    windows = [TumblingWindow(Time, 100)]

    def make(mesh):
        p = KeyedAlignedPipeline(
            windows, [SumAggregation()], n_keys=K, config=CFG,
            throughput=K * 1000, wm_period_ms=100, max_lateness=100,
            seed=21, gc_every=4, mesh=mesh)
        p.reset()
        return p

    p_mesh = make(make_mesh("keys"))
    p_solo = make(None)
    for i in range(6):
        a = p_mesh.run(1)[0]
        b = p_solo.run(1)[0]
        for kk in (0, 3, K - 1):
            ra = p_mesh.lowered_results_for_key(a, kk)
            rb = p_solo.lowered_results_for_key(b, kk)
            assert [(s, e, c) for s, e, c, _ in ra] == \
                   [(s, e, c) for s, e, c, _ in rb], (i, kk)
            for (_, _, _, va), (_, _, _, vb) in zip(ra, rb):
                for x, y in zip(va, vb):
                    assert float(x) == float(y), (i, kk)
    p_mesh.check_overflow()
    p_solo.check_overflow()


def test_keyed_aligned_pipeline_matches_simulator():
    """The fused keyed pipeline (one dispatch per interval, [K, S, R]
    slice-grouped generation) must emit, for a sampled key, the same
    windows as the host simulator fed that key's regenerated stream."""
    import pytest

    from scotty_tpu import MaxAggregation, SlicingWindowOperator
    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    K = 8
    windows = [TumblingWindow(Time, 100), SlidingWindow(Time, 500, 100)]
    p = KeyedAlignedPipeline(
        windows, [SumAggregation(), MaxAggregation()], n_keys=K,
        config=CFG, throughput=K * 2000, wm_period_ms=100,
        max_lateness=100, seed=13, gc_every=3)
    sims = []
    for _ in range(2):                      # sample two keys
        sim = SlicingWindowOperator()
        for w in windows:
            sim.add_window_assigner(w)
        sim.add_aggregation(SumAggregation())
        sim.add_aggregation(MaxAggregation())
        sim.set_max_lateness(100)
        sims.append(sim)
    sample_keys = [0, K - 1]

    p.reset()
    for i in range(8):
        out = p.run(1)[0]
        for sim, kk in zip(sims, sample_keys):
            vals, ts = p.materialize_interval(i, kk)
            order = np.argsort(ts, kind="stable")
            sim.process_elements(vals[order], ts[order])
            want = {}
            for w in sim.process_watermark((i + 1) * 100):
                if w.has_value():
                    want.setdefault((w.get_start(), w.get_end()),
                                    w.get_agg_values())
            got = {(s, e): v
                   for (s, e, c, v) in p.lowered_results_for_key(out, kk)}
            assert set(got) == set(want), (i, kk, set(want) ^ set(got))
            for k2 in want:
                for a, b in zip(want[k2], got[k2]):
                    assert float(a) == pytest.approx(float(b), rel=2e-4), \
                        (i, kk, k2)
    p.check_overflow()


def test_global_operator_sparse_agg_hll():
    """Sparse-lift aggregations (HLL registers = max-kind partials) work
    through the global operator's collective combine: the merged distinct
    count over all shards matches one host HLL fed the same values."""
    from scotty_tpu import HyperLogLogAggregation

    rng = np.random.default_rng(8)
    N = 2000
    vals = rng.integers(0, 500, size=N).astype(np.float64)  # ~430 distinct
    ts = np.sort(rng.integers(0, 100, size=N))

    op = GlobalTpuWindowOperator(n_shards=8, config=CFG,
                                 mesh=make_mesh("shards"))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(HyperLogLogAggregation(8))
    op.process_elements(vals, ts)
    got = [w for w in op.process_watermark(200) if w.has_value()]
    assert len(got) == 1
    est = float(got[0].get_agg_values()[0])
    true_distinct = len(np.unique(vals))
    # HLL with p=8: ~6.5% standard error; allow 3 sigma
    assert abs(est - true_distinct) / true_distinct < 0.2, (est,
                                                           true_distinct)
