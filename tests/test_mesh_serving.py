"""Multi-tenant mesh serving + elastic reshard (ISSUE 13): the fused
shard_map serving step, churn differentials against an always-active
superset oracle, cancel→re-register slot recycling with generation
checks across a reshard, shard-aware admission under tenant affinity,
and the supervised exactly-once loop — all on the conftest-provided
virtual 8-device CPU mesh."""

import os

import numpy as np
import pytest

from scotty_tpu import (
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu import obs as _obs
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.pipeline import SlotGeometry
from scotty_tpu.mesh_serving import (
    MeshQueryService,
    MeshServingPipeline,
    run_supervised_mesh,
    tenant_home_shard,
)
from scotty_tpu.serving import QueryAdmission, QueryRejected

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=64, annex_capacity=8, min_trigger_pad=32)


def make_service(shards=8, max_queries=8, seed=3, obs=None, quota=0,
                 shard_quota=0, on_reject="fail", windows=(),
                 trace_cell=None, n_keys=16):
    return MeshQueryService(
        [SumAggregation()], slice_grid=500, max_window_size=4000,
        n_keys=n_keys, n_shards=shards, throughput=n_keys * 1000,
        wm_period_ms=1000, max_lateness=1000, seed=seed, config=CFG,
        admission=QueryAdmission(max_queries=max_queries,
                                 per_tenant_quota=quota,
                                 per_shard_quota=shard_quota,
                                 on_reject=on_reject),
        windows=list(windows), obs=obs, trace_cell=trace_cell)


# ---------------------------------------------------------------------------
# The fused serving step
# ---------------------------------------------------------------------------


def test_pipeline_per_key_matches_host_simulator_and_global_fold():
    """Per-key rows of a mid-stream-registered query bit-follow a host
    simulator replay of that key's materialized stream, and the psum
    global fold equals the per-key column sum — with ZERO retraces
    across the register (one row write, table data)."""
    geom = SlotGeometry(n_slots=8, triggers_per_slot=4, slice_grid=500,
                        max_size=4000)
    p = MeshServingPipeline(
        [SumAggregation()], query_slots=geom, n_keys=16, n_shards=8,
        config=CFG, throughput=16 * 1000, wm_period_ms=1000,
        max_lateness=1000, seed=5)
    p.reset()
    p.write_query_slot(0, 0, 1000, 1000, True)       # Tumbling(1000)
    p.run(2, collect=False)
    p.sync()
    traces = p._trace_count
    # register Sliding(2000, 500) MID-STREAM: answers over slices
    # ingested before it existed (shared slicing at mesh scale)
    p.write_query_slot(1, 1, 500, 2000, True)
    outs = p.run(2)
    assert p._trace_count == traces                  # zero retraces

    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 1000))
    sim.add_window_assigner(SlidingWindow(Time, 2000, 500))
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(1000)
    key = 7
    for i in range(2):
        vals, ts = p.materialize_interval(i, key)
        sim.process_elements(vals, ts)
        sim.process_watermark((i + 1) * 1000)
    for j, i in enumerate((2, 3)):
        vals, ts = p.materialize_interval(i, key)
        sim.process_elements(vals, ts)
        want = {}
        for w in sim.process_watermark((i + 1) * 1000):
            if w.has_value():
                want[(w.get_start(), w.get_end())] = w.get_agg_values()
        got = {(s, e): v for (s, e, c, v)
               in p.lowered_results_for_key(outs[j], key)}
        assert set(got) == set(want), (i, sorted(got), sorted(want))
        for k2 in want:
            for x, y in zip(want[k2], got[k2]):
                assert abs(float(x) - float(y)) \
                    <= 2e-4 * max(1.0, abs(float(x)))
        # the in-executable psum fold == the per-key column sum
        import jax

        ws, we, cnt, _res, gcnt, _gp = jax.device_get(outs[j])
        assert (gcnt == cnt.sum(axis=0)).all()
    p.check_overflow()


# ---------------------------------------------------------------------------
# Churn differential: always-active superset oracle
# ---------------------------------------------------------------------------


def test_churn_bitmatches_always_active_superset():
    """Queries registered/cancelled mid-stream answer BIT-IDENTICALLY
    (global psum fold AND sampled per-key rows) to a superset service
    that had every query active from the start — engine state is
    query-set independent and per-trigger-row results are independent,
    so exact f32 byte equality is demanded."""
    svc = make_service(windows=[TumblingWindow(Time, 1000)])
    sup = make_service(max_queries=16,
                       windows=[TumblingWindow(Time, 1000)])
    w_a = SlidingWindow(Time, 2000, 500)
    w_b = TumblingWindow(Time, 500)
    ha_o = sup.register(w_a, tenant="acme")
    hb_o = sup.register(w_b, tenant="beta")
    sup.run(1, collect=False)

    svc.run(1, collect=False)
    svc.sync()
    svc.mark_warm()
    ha = svc.register(w_a, tenant="acme")            # interval 1
    keys = (0, 5, 15)
    for i in (1, 2):
        o_s, o_o = svc.run(1)[0], sup.run(1)[0]
        assert svc.global_rows_by_slot(o_s)[ha.slot] \
            == sup.global_rows_by_slot(o_o)[ha_o.slot]
        for k in keys:
            assert svc.key_rows_by_slot(o_s, k).get(ha.slot) \
                == sup.key_rows_by_slot(o_o, k).get(ha_o.slot)
    svc.cancel(ha)
    hb = svc.register(w_b, tenant="beta")            # recycles ha's slot
    assert hb.slot == ha.slot and hb.gen == ha.gen + 1
    o_s, o_o = svc.run(1)[0], sup.run(1)[0]
    assert svc.global_rows_by_slot(o_s).get(hb.slot) \
        == sup.global_rows_by_slot(o_o).get(hb_o.slot)
    # the cancelled query's rows are gone (masked), not stale
    assert ha.slot not in svc.key_rows_by_slot(o_s, 5) \
        or svc.key_rows_by_slot(o_s, 5)[hb.slot] \
        == sup.key_rows_by_slot(o_o, 5)[hb_o.slot]
    assert svc.retraces_since_warm == 0
    svc.check_overflow(), sup.check_overflow()


# ---------------------------------------------------------------------------
# Elastic reshard
# ---------------------------------------------------------------------------


def test_reshard_bitmatches_and_recycles_slots_with_generations(tmp_path):
    """The 8→4→8 walk: emissions bit-match an un-resharded twin;
    cancel→re-register across a reshard recycles the slot LIFO with the
    generation bumped; a stale pre-reshard handle copy is rejected; the
    reshard compiles are itemized apart from steady-state retraces."""
    from scotty_tpu.resilience import ManualClock, Supervisor

    svc = make_service(windows=[TumblingWindow(Time, 1000)])
    twin = make_service(windows=[TumblingWindow(Time, 1000)])
    h = svc.register(SlidingWindow(Time, 2000, 500), tenant="acme")
    th = twin.register(SlidingWindow(Time, 2000, 500), tenant="acme")
    svc.run(2, collect=False)
    svc.sync()
    svc.mark_warm()
    sup = Supervisor(os.path.join(str(tmp_path), "ck"),
                     clock=ManualClock(), seed=1)
    twin.run(2, collect=False)

    r = svc.reshard(4, sup, pos=svc.interval)
    assert r["resharded"] and r["from"] == 8 and r["to"] == 4
    assert svc.n_shards == 4 and svc.reshard_retraces == 1
    o, t = svc.run(1)[0], twin.run(1)[0]
    # per-key rows are shard-local state: EXACT across shard counts.
    # The global fold's psum reduction tree changes with the shard
    # count (4 vs 8 partials), so across-count comparisons are
    # tolerance-equal — equal-count phases below go back to exact.
    for k in (0, 9):
        assert svc.key_rows_by_slot(o, k) == twin.key_rows_by_slot(t, k)
    g_s, g_t = svc.global_rows_by_slot(o), twin.global_rows_by_slot(t)
    assert set(g_s) == set(g_t)
    for slot in g_s:
        for (s1, e1, c1, v1), (s2, e2, c2, v2) in zip(g_s[slot],
                                                      g_t[slot]):
            assert (s1, e1, c1) == (s2, e2, c2)
            np.testing.assert_allclose(np.float64(v1), np.float64(v2),
                                       rtol=1e-6)

    # churn ACROSS the reshard: cancel, then re-register — LIFO recycle,
    # generation bumped, the pre-reshard stale copy is dead
    stale = h
    svc.cancel(h)
    twin.cancel(th)
    h2 = svc.register(TumblingWindow(Time, 500), tenant="beta")
    th2 = twin.register(TumblingWindow(Time, 500), tenant="beta")
    assert h2.slot == stale.slot and h2.gen == stale.gen + 1
    with pytest.raises(ValueError, match="stale or unknown"):
        svc.cancel(stale)

    r = svc.reshard(8, sup, pos=svc.interval)
    assert r["to"] == 8
    # returning to 8 shards re-enters the warm bucket: no new compile
    assert svc.reshard_retraces == 1
    o, t = svc.run(1)[0], twin.run(1)[0]
    assert svc.global_rows_by_slot(o) == twin.global_rows_by_slot(t)
    assert svc.global_rows_by_slot(o)[h2.slot] \
        == twin.global_rows_by_slot(t)[th2.slot]
    assert svc.retraces_since_warm == 0
    assert [row["to"] for row in svc.reshard_timeline] == [4, 8]
    svc.check_overflow(), twin.check_overflow()


def test_reshard_rejects_indivisible_shard_count(tmp_path):
    from scotty_tpu.resilience import ManualClock, Supervisor

    svc = make_service(windows=[TumblingWindow(Time, 1000)])
    svc.run(1, collect=False)
    sup = Supervisor(os.path.join(str(tmp_path), "ck"),
                     clock=ManualClock(), seed=1)
    with pytest.raises(ValueError, match="multiple of the shard count"):
        svc.reshard(5, sup, pos=1)


def test_checkpoint_restores_active_set_at_other_shard_count(tmp_path):
    """The query table checkpoints atomically alongside mesh state: a
    bundle saved under 8 shards restores into a FRESH 4-shard service,
    replaying the exact active set (slots, generations, tenants) and
    continuing the emission stream bit-identically."""
    svc = make_service(windows=[TumblingWindow(Time, 1000)])
    h = svc.register(SlidingWindow(Time, 2000, 1000), tenant="acme")
    svc.run(3, collect=False)
    svc.sync()
    d = os.path.join(str(tmp_path), "snap")
    svc.save(d)
    cont = svc.run(1)[0]

    fresh = make_service(shards=4)
    fresh.restore(d)
    assert fresh.table.n_active == 2
    assert fresh.active_handles()[h.slot].tenant == "acme"
    assert fresh.active_handles()[h.slot].gen == h.gen
    out = fresh.run(1)[0]
    # per-key rows are shard-local: exact across the 8→4 restore; the
    # global psum tree differs with shard count (tolerance there)
    for k in (0, 5, 15):
        assert fresh.key_rows_by_slot(out, k) \
            == svc.key_rows_by_slot(cont, k)
    g_f, g_s = fresh.global_rows_by_slot(out), svc.global_rows_by_slot(cont)
    assert set(g_f) == set(g_s)
    for slot in g_f:
        for (s1, e1, c1, v1), (s2, e2, c2, v2) in zip(g_f[slot],
                                                      g_s[slot]):
            assert (s1, e1, c1) == (s2, e2, c2)
            np.testing.assert_allclose(np.float64(v1), np.float64(v2),
                                       rtol=1e-6)
    # generation continuity: the restored handle cancels cleanly
    fresh.cancel(fresh.active_handles()[h.slot])
    assert fresh.table.n_active == 1


# ---------------------------------------------------------------------------
# Shard-aware admission under tenant affinity
# ---------------------------------------------------------------------------


def _same_home_tenants(n: int, shards: int = 8):
    """n distinct tenant names hashing to one affinity home shard."""
    home = tenant_home_shard("t0", shards)
    out, i = ["t0"], 1
    while len(out) < n:
        cand = f"t{i}"
        if tenant_home_shard(cand, shards) == home:
            out.append(cand)
        i += 1
    return out


def test_admission_shard_quota_under_tenant_affinity():
    """per_shard_quota caps the active queries any one affinity home
    shard carries — tenants hashing to DIFFERENT shards are unaffected,
    and the rejection names the shard reason."""
    a, b = _same_home_tenants(2)
    other = next(f"x{i}" for i in range(64)
                 if tenant_home_shard(f"x{i}", 8)
                 != tenant_home_shard(a, 8))
    svc = make_service(max_queries=8, shard_quota=2)
    svc.register(TumblingWindow(Time, 1000), tenant=a)
    svc.register(TumblingWindow(Time, 500), tenant=b)
    with pytest.raises(QueryRejected) as ei:
        svc.register(TumblingWindow(Time, 2000), tenant=a)
    assert ei.value.reason == "shard"
    # a tenant on another home shard still admits
    assert svc.register(TumblingWindow(Time, 1000), tenant=other)


def test_admission_shed_and_quota_counted_on_mesh():
    shed = []
    svc = MeshQueryService(
        [SumAggregation()], slice_grid=500, max_window_size=4000,
        n_keys=16, n_shards=8, throughput=16_000, wm_period_ms=1000,
        max_lateness=1000, seed=3, config=CFG,
        admission=QueryAdmission(
            max_queries=8, per_tenant_quota=1, per_shard_quota=0,
            on_reject="shed",
            reject_callback=lambda w, t, r: shed.append((t, r))))
    assert svc.register(TumblingWindow(Time, 1000), tenant="acme")
    assert svc.register(TumblingWindow(Time, 500), tenant="acme") is None
    assert shed == [("acme", "quota")]
    assert svc.stats()["serving_rejected"] == 1


def test_mesh_tenant_gauges_ride_topk_rollup():
    """The mesh service shares the capped-cardinality gauge helper:
    top-k named gauges + serving_tenant_other, zero-on-cancel intact."""
    obs = _obs.Observability()
    svc = MeshQueryService(
        [SumAggregation()], slice_grid=500, max_window_size=4000,
        n_keys=16, n_shards=8, throughput=16_000, wm_period_ms=1000,
        max_lateness=1000, seed=3, config=CFG,
        admission=QueryAdmission(max_queries=8),
        tenant_gauge_top_k=2, obs=obs)
    h_a1 = svc.register(TumblingWindow(Time, 1000), tenant="alice")
    svc.register(TumblingWindow(Time, 500), tenant="alice")
    svc.register(TumblingWindow(Time, 1000), tenant="bob")
    svc.register(TumblingWindow(Time, 2000), tenant="carol")
    snap = obs.snapshot()
    assert snap["serving_tenant_active_alice"] == 2
    assert snap["serving_tenant_active_bob"] == 1
    assert snap["serving_tenant_other"] == 1          # carol rolled up
    svc.cancel(h_a1)
    snap = obs.snapshot()
    # alice dropped to 1 — ties break by name: alice+bob stay named
    assert snap["serving_tenant_active_alice"] == 1
    assert snap["serving_tenant_other"] == 1
    kinds = {e["kind"] for e in obs.flight.events()} if obs.flight else ()


# ---------------------------------------------------------------------------
# Supervised exactly-once loop (crash-free determinism; the armed-fault
# sweep lives in test_mesh_serving_crash.py)
# ---------------------------------------------------------------------------


def test_supervised_loop_is_deterministic_and_duplicate_free(tmp_path):
    from scotty_tpu.delivery import EXACTLY_ONCE, TransactionalSink
    from scotty_tpu.resilience import ManualClock, Supervisor

    churn = {1: [("register", SlidingWindow(Time, 2000, 500), "acme")],
             3: [("cancel_one", "acme"),
                 ("register", TumblingWindow(Time, 500), "beta")]}
    reshard_at = {2: 4, 4: 8}

    def run(d):
        sup = Supervisor(os.path.join(str(tmp_path), d),
                         clock=ManualClock(), seed=1, max_restarts=4)
        sink = TransactionalSink(mode=EXACTLY_ONCE)
        return run_supervised_mesh(
            lambda s: make_service(
                shards=s, windows=[TumblingWindow(Time, 1000)]),
            5, sup, sink=sink, churn=churn, reshard_at=reshard_at,
            initial_shards=8, checkpoint_every=2)

    a, b = run("a"), run("b")
    assert a == b and len(a) > 0
    # every (interval, slot, gen) triple delivered exactly once
    ids = [(i, s, g) for (i, s, g, _rows) in a]
    assert len(ids) == len(set(ids))


def test_mesh_churn_bench_cell_smoke():
    """run_query_churn_mesh_cell completes on a tiny geometry with the
    full contract: zero steady-state retraces (trace-reconciled), the
    8→4→8 reshard timeline, superset-oracle bit-match, unique delivery
    tags."""
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_query_churn_mesh_cell

    cfg = BenchmarkConfig(
        name="mesh-churn-smoke", throughput=1 << 17, runtime_s=5,
        capacity=64, n_keys=128, n_shards=8, watermark_period_ms=1000,
        max_lateness=1000, churn_ops=40, churn_max_active=12,
        churn_tenants=3, mesh_reshard_schedule=[[2, 4], [4, 8]])
    r = run_query_churn_mesh_cell(cfg, "Sliding(2000,500)", "sum")
    assert r.tuples_per_sec > 0
    assert r.oracle_match and r.delivery_tags_unique
    assert r.serving_retraces_after_warmup == 0
    assert r.churn_ops >= 40
    assert [row["to"] for row in r.reshard_timeline] == [4, 8]
    assert r.n_keys == 128 and r.n_shards == 8
