"""Assertion helpers mirroring the reference's WindowAssert
(slicing/src/test/.../windowTest/WindowAssert.java:10-24)."""

from __future__ import annotations


def assert_window(window, start, end, value):
    assert window.get_start() == start, f"start {window.get_start()} != {start} ({window})"
    assert window.get_end() == end, f"end {window.get_end()} != {end} ({window})"
    assert window.get_agg_values()[0] == value, (
        f"value {window.get_agg_values()} != {value} ({window})")


def assert_contains(windows, start, end, value):
    for w in windows:
        if (w.get_start() == start and w.get_end() == end
                and w.has_value() and w.get_agg_values()[0] == value):
            return
    raise AssertionError(
        f"no window ({start},{end},{value}) in {[repr(w) for w in windows]}")
