"""Stream-shaper differential + behavior tests (ISSUE 5).

The oracle discipline of the rest of the suite: the device sort-and-split
must bit-match the numpy mirror on seeded chaos streams, and end-to-end
window results through the shaped device path must bit-match the host
reference-semantics simulator. Chaos values are small integers (exactly
representable in float32) so every comparison is exact.
"""

import numpy as np
import pytest

from scotty_tpu import (
    SlicingWindowOperator,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig, TpuWindowOperator
from scotty_tpu.resilience import chaos
from scotty_tpu.resilience.clock import ManualClock
from scotty_tpu.shaper import (
    BatchAccumulator,
    ShaperConfig,
    ShaperOverflow,
    StreamShaper,
    count_reordered,
    init_shaper_stats,
    keyed_round_host,
    keyed_round_kernel,
    sort_split_host,
    sort_split_kernel,
)
from scotty_tpu.shaper.device import I64_MIN, stats_snapshot

Time = WindowMeasure.Time

SMALL = EngineConfig(capacity=1 << 12, batch_size=64, annex_capacity=256,
                     min_trigger_pad=32)


# ---------------------------------------------------------------------------
# device sort-and-split vs numpy oracle
# ---------------------------------------------------------------------------


def _chaos_batch(kind: str, seed: int, n: int):
    """Seeded chaos batches: (vals, ts, cut) per disorder pattern."""
    if kind == "burst":
        vals, ts = chaos.burst(seed, n, 0, 10_000)
        order = chaos.rng_of(seed + 1).permutation(n)
        return vals[order], ts[order], 5_000
    if kind == "late_storm":
        vals, ts = chaos.late_storm(seed, n, now_ts=8_000,
                                    max_lateness=6_000)
        return vals, ts, 8_000            # everything late
    if kind == "duplicates":
        rng = chaos.rng_of(seed)
        ts = rng.integers(0, 8, size=n).astype(np.int64) * 1000
        vals = rng.integers(0, 256, size=n).astype(np.float32)
        return vals, ts, 3_500
    if kind == "none_late":
        rng = chaos.rng_of(seed)
        ts = rng.integers(5_000, 9_000, size=n).astype(np.int64)
        vals = rng.integers(0, 256, size=n).astype(np.float32)
        return vals, ts, 5_000
    raise AssertionError(kind)


@pytest.mark.parametrize("kind", ["burst", "late_storm", "duplicates",
                                  "none_late"])
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_sort_split_matches_numpy_oracle(kind, seed):
    import jax

    B = 128
    vals, ts, cut = _chaos_batch(kind, seed, B)
    valid = np.ones(B, bool)
    kern = sort_split_kernel(B, B)        # residue can never overflow
    stats, io_ts, io_vals, io_valid, l_ts, l_vals, l_valid = kern(
        init_shaper_stats(), ts, vals, valid, np.int64(cut),
        np.int64(I64_MIN))
    o_iov, o_iot, o_lv, o_lt = sort_split_host(vals, ts, cut)
    n_io = int(np.asarray(io_valid).sum())
    n_l = int(np.asarray(l_valid).sum())
    assert n_io == o_iot.size and n_l == o_lt.size
    assert (np.asarray(io_ts)[:n_io] == o_iot).all()
    assert (np.asarray(io_vals)[:n_io] == o_iov).all()
    assert (np.asarray(l_ts)[:n_l] == o_lt).all()
    assert (np.asarray(l_vals)[:n_l] == o_lv).all()
    if n_io:
        # pad lanes repeat the max valid ts (the device-batch contract)
        assert (np.asarray(io_ts)[n_io:] == o_iot[-1]).all()
    snap = stats_snapshot(jax.device_get(stats))
    assert snap["seen"] == B
    assert snap["late_routed"] == n_l
    assert not snap["slack_overflow"]
    assert snap["reordered"] == count_reordered(ts, None)


def test_sort_split_partial_and_single_and_empty():
    import jax

    B = 32
    rng = np.random.default_rng(0)
    ts = rng.integers(0, 1000, size=B).astype(np.int64)
    vals = rng.integers(0, 64, size=B).astype(np.float32)
    kern = sort_split_kernel(B, B)
    for n in (1, 7, 0):
        valid = np.zeros(B, bool)
        valid[:n] = True
        stats, io_ts, io_vals, io_valid, l_ts, l_vals, l_valid = kern(
            init_shaper_stats(), ts, vals, valid, np.int64(500),
            np.int64(I64_MIN))
        o_iov, o_iot, o_lv, o_lt = sort_split_host(vals[:n], ts[:n], 500)
        n_io = int(np.asarray(io_valid).sum())
        n_l = int(np.asarray(l_valid).sum())
        assert n_io == o_iot.size and n_l == o_lt.size
        assert (np.asarray(io_ts)[:n_io] == o_iot).all()
        assert (np.asarray(l_ts)[:n_l] == o_lt).all()
        assert stats_snapshot(jax.device_get(stats))["seen"] == n


def test_sort_split_slack_overflow_flag_sticky():
    import jax

    B, L = 64, 8
    rng = np.random.default_rng(1)
    ts = rng.integers(0, 1000, size=B).astype(np.int64)   # ALL below cut
    vals = np.ones(B, np.float32)
    valid = np.ones(B, bool)
    kern = sort_split_kernel(B, L)
    stats = init_shaper_stats()
    out = kern(stats, ts, vals, valid, np.int64(5000), np.int64(I64_MIN))
    assert stats_snapshot(jax.device_get(out[0]))["slack_overflow"]
    # sticky across a subsequent clean batch
    clean = np.sort(ts) + 10_000
    out2 = kern(out[0], clean, vals, valid, np.int64(5000),
                np.int64(5000))
    assert stats_snapshot(jax.device_get(out2[0]))["slack_overflow"]


@pytest.mark.parametrize("seed", [0, 3])
def test_keyed_round_matches_numpy_oracle(seed):
    K, Bk, N = 8, 64, 180
    rng = chaos.rng_of(seed)
    keys = rng.integers(0, K, size=N).astype(np.int64)
    ts = rng.integers(0, 5000, size=N).astype(np.int64)
    vals = rng.integers(0, 100, size=N).astype(np.float32)
    kern = keyed_round_kernel(K, Bk)
    stats, tr, vr, m = kern(init_shaper_stats(), keys, ts, vals,
                            np.ones(N, bool), np.int64(I64_MIN))
    o_tr, o_vr, o_m, _ = keyed_round_host(keys, vals, ts, K, Bk)
    assert (np.asarray(m) == o_m).all()
    assert (np.asarray(tr) == o_tr).all()
    assert (np.asarray(vr) == o_vr).all()


def test_keyed_round_row_overflow_flags():
    import jax

    K, Bk, N = 2, 4, 12
    keys = np.zeros(N, np.int64)          # one key holds all 12 > Bk=4
    ts = np.arange(N, dtype=np.int64)
    vals = np.ones(N, np.float32)
    kern = keyed_round_kernel(K, Bk)
    stats, _, _, _ = kern(init_shaper_stats(), keys, ts, vals,
                          np.ones(N, bool), np.int64(I64_MIN))
    assert stats_snapshot(jax.device_get(stats))["slack_overflow"]


# ---------------------------------------------------------------------------
# end-to-end: shaped OOO device stream bit-matches the host simulator
# ---------------------------------------------------------------------------


def _mk_engine(shaper_cfg=None, windows=None):
    op = TpuWindowOperator(config=SMALL)
    for w in windows or [SlidingWindow(Time, 2000, 500)]:
        op.add_window_assigner(w)
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(4000)
    return op, StreamShaper(op, shaper_cfg or ShaperConfig(late_capacity=64))


def _mk_sim(windows=None):
    sim = SlicingWindowOperator()
    for w in windows or [SlidingWindow(Time, 2000, 500)]:
        sim.add_window_assigner(w)
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(4000)
    return sim


def _windows_dict(ws, we, cnt, lowered):
    return {(int(s), int(e)): float(v)
            for s, e, c, v in zip(ws, we, cnt, lowered[0]) if c > 0}


def _sim_dict(results):
    return {(w.start, w.end): float(w.agg_values[0])
            for w in results if w.has_value()}


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_shaped_device_ooo_bitmatches_simulator(seed):
    import jax

    B = SMALL.batch_size
    rng = chaos.rng_of(seed)
    op, shaper = _mk_engine()
    sim = _mk_sim()
    wm = 0
    for i in range(6):
        lo = i * 1000
        ts = rng.integers(max(0, lo - 3000), lo + 1000,
                          size=B).astype(np.int64)
        vals = rng.integers(0, 256, size=B).astype(np.float32)
        shaper.shape_device_batch(jax.device_put(vals),
                                  jax.device_put(ts),
                                  int(ts.min()), int(ts.max()))
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
        if i % 2 == 1:
            wm = lo + 1000
            got = _windows_dict(*op.process_watermark_arrays(wm))
            exp = _sim_dict(sim.process_watermark(wm))
            assert got == exp
    got = _windows_dict(*op.process_watermark_arrays(wm + 5000))
    exp = _sim_dict(sim.process_watermark(wm + 5000))
    assert got == exp
    op.check_overflow()
    stats = shaper.device_stats()
    assert stats["seen"] == 6 * B
    assert stats["late_routed"] > 0     # the chaos streams ARE disordered


def test_shaped_device_combined_routing_bitmatches():
    import jax

    seed = 5
    B = SMALL.batch_size
    rng = chaos.rng_of(seed)
    op, shaper = _mk_engine(ShaperConfig(late_routing="combined"))
    sim = _mk_sim()
    for i in range(4):
        lo = i * 1000
        ts = rng.integers(max(0, lo - 2000), lo + 1000,
                          size=B).astype(np.int64)
        vals = rng.integers(0, 256, size=B).astype(np.float32)
        shaper.shape_device_batch(jax.device_put(vals),
                                  jax.device_put(ts),
                                  int(ts.min()), int(ts.max()))
        for v, t in zip(vals, ts):
            sim.process_element(float(v), int(t))
    got = _windows_dict(*op.process_watermark_arrays(9000))
    exp = _sim_dict(sim.process_watermark(9000))
    assert got == exp
    op.check_overflow()


def test_shaped_device_slack_overflow_raises_at_drain():
    import jax

    from scotty_tpu import obs as obs_mod

    B = SMALL.batch_size
    obs = obs_mod.Observability(flight=obs_mod.FlightRecorder(64))
    op = TpuWindowOperator(config=SMALL, obs=obs)
    op.add_window_assigner(TumblingWindow(Time, 1000))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    shaper = StreamShaper(op, ShaperConfig(late_capacity=8))
    rng = np.random.default_rng(0)
    # establish a stream head, then a late storm far beyond 8 lanes
    ts0 = np.sort(rng.integers(8000, 9000, size=B)).astype(np.int64)
    vals = np.ones(B, np.float32)
    shaper.shape_device_batch(jax.device_put(vals), jax.device_put(ts0),
                              8000, 9000)
    late = rng.integers(0, 4000, size=B).astype(np.int64)
    shaper.shape_device_batch(jax.device_put(vals),
                              jax.device_put(late), 0, 4000)
    with pytest.raises(ShaperOverflow):
        op.check_overflow()
    snap = obs.snapshot()
    assert snap["shaper_slack_overflows"] >= 1
    kinds = [e["kind"] for e in obs.flight.events()]
    assert "shaper_overflow" in kinds


# ---------------------------------------------------------------------------
# host accumulator
# ---------------------------------------------------------------------------


def test_accumulator_coalesces_and_sorts():
    blocks = []
    acc = BatchAccumulator(4, lambda v, t: blocks.append((v, t)))
    rng = np.random.default_rng(0)
    ts = rng.permutation(12).astype(np.int64)
    for t in ts:
        acc.offer(float(t), int(t))
    assert [b[1].size for b in blocks] == [4, 4, 4]
    for _, bt in blocks:
        assert (np.diff(bt) >= 0).all()         # sorted within each block
    merged = np.concatenate([b[1] for b in blocks])
    assert sorted(merged.tolist()) == sorted(ts.tolist())   # nothing lost
    assert acc.held == 0
    assert acc.flushes == 3
    assert acc.reordered == count_reordered(ts, None)
    assert acc.fill_ratios == [1.0, 1.0, 1.0]


def test_accumulator_reorder_slack_holds_newest_band():
    blocks = []
    acc = BatchAccumulator(2, lambda v, t: blocks.append(t.tolist()),
                           slack_ms=100)
    acc.offer([1.0, 1.0, 1.0, 1.0], [10, 20, 500, 510])
    # emittable horizon = 510 - 100 = 410: only (10, 20) may flush
    assert blocks == [[10, 20]]
    assert acc.held == 2
    # a straggler below the held band still merges in sorted order
    acc.offer(1.0, 450)
    acc.drain()
    assert blocks[1:] == [[450, 500], [510]]


def test_accumulator_bounded_delay_flush_on_manual_clock():
    clock = ManualClock()
    blocks = []
    acc = BatchAccumulator(100, lambda v, t: blocks.append(t.tolist()),
                           max_delay_ms=50, clock=clock)
    acc.offer(1.0, 5)
    acc.offer(1.0, 3)
    assert blocks == []                  # under-full, deadline not reached
    clock.advance(0.049)
    assert acc.poll() == 0
    clock.advance(0.002)                 # past the 50 ms deadline
    assert acc.poll() == 1
    assert blocks == [[3, 5]]            # partial block, sorted
    assert acc.held == 0
    # the deadline re-arms from the next first record
    acc.offer(1.0, 9)
    assert blocks == [[3, 5]]
    clock.advance(0.051)
    acc.offer(1.0, 7)                    # offer past deadline also flushes
    assert blocks == [[3, 5], [7, 9]]


def test_accumulator_keyed_object_payloads():
    blocks = []
    acc = BatchAccumulator(3, lambda k, v, t: blocks.append((list(k),
                                                             list(v),
                                                             t.tolist())),
                           keyed=True, value_dtype=None)
    acc.offer([("tup", 1), "plain", ("tup", 2)], [30, 10, 20],
              keys=["b", "a", "c"])
    assert blocks == [(["a", "c", "b"],
                       ["plain", ("tup", 2), ("tup", 1)], [10, 20, 30])]


# ---------------------------------------------------------------------------
# operator + connector wiring
# ---------------------------------------------------------------------------


def test_operator_shaper_trickle_feed_bitmatches_simulator():
    op = TpuWindowOperator(config=SMALL, shaper=ShaperConfig())
    op.add_window_assigner(SlidingWindow(Time, 2000, 500))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(4000)
    assert op.shaper is not None
    sim = _mk_sim()
    rng = chaos.rng_of(7)
    for i in range(3):
        lo = i * 1000
        ts = rng.integers(max(0, lo - 2000), lo + 1000,
                          size=150).astype(np.int64)
        vals = rng.integers(0, 256, size=150).astype(np.float32)
        for v, t in zip(vals, ts):       # the per-record trickle
            op.process_element(float(v), int(t))
            sim.process_element(float(v), int(t))
    # the watermark must drain records still held in the accumulator
    assert op.shaper.held > 0
    got = _windows_dict(*op.process_watermark_arrays(6000))
    exp = _sim_dict(sim.process_watermark(6000))
    assert got == exp
    assert op.shaper.held == 0
    op.check_overflow()


def test_operator_shaper_rejects_wrong_type():
    with pytest.raises(TypeError):
        TpuWindowOperator(config=SMALL, shaper=object())


def _bounded_ooo_records(seed, n, step=20, jitter=400):
    rng = chaos.rng_of(seed)
    base = np.arange(n) * step
    ts = np.maximum(base + rng.integers(-jitter, jitter, n), 0)
    vals = rng.integers(0, 100, n)
    return vals, ts


def test_run_global_shaper_equals_sorted_unshaped():
    from scotty_tpu.connectors.base import (
        AscendingWatermarks,
        GlobalScottyWindowOperator,
    )
    from scotty_tpu.connectors.iterable import collect_global

    vals, ts = _bounded_ooo_records(3, 400)
    recs = [(float(v), int(t)) for v, t in zip(vals, ts)]

    def mk(shaper=None):
        return GlobalScottyWindowOperator(
            windows=[TumblingWindow(Time, 1000)],
            aggregations=[SumAggregation()], allowed_lateness=1000,
            watermark_policy=AscendingWatermarks(), shaper=shaper)

    out_s = collect_global(iter(recs),
                           mk(ShaperConfig(batch_size=64, slack_ms=1000)),
                           final_watermark=20_000)
    out_r = collect_global(iter(sorted(recs, key=lambda r: r[1])), mk(),
                           final_watermark=20_000)
    key = lambda w: (w.start, w.end, tuple(w.agg_values))  # noqa: E731
    assert sorted(map(key, out_s)) == sorted(map(key, out_r))


def test_run_keyed_shaper_equals_sorted_unshaped():
    from scotty_tpu.connectors.base import (
        AscendingWatermarks,
        KeyedScottyWindowOperator,
    )
    from scotty_tpu.connectors.iterable import collect_keyed, run_keyed

    vals, ts = _bounded_ooo_records(4, 400)
    rng = chaos.rng_of(11)
    keys = rng.integers(0, 3, vals.size)
    recs = [(f"k{int(k)}", float(v), int(t))
            for k, v, t in zip(keys, vals, ts)]

    def mk():
        return KeyedScottyWindowOperator(
            windows=[TumblingWindow(Time, 1000)],
            aggregations=[SumAggregation()], allowed_lateness=1000,
            watermark_policy=AscendingWatermarks())

    # shaper= on the run loop itself (the ISSUE 5 wiring face)
    op_s = mk()
    out_s = list(run_keyed(iter(recs), op_s,
                           shaper=ShaperConfig(batch_size=64,
                                               slack_ms=1000)))
    out_s += op_s.process_watermark(20_000)
    out_r = collect_keyed(iter(sorted(recs, key=lambda r: r[2])), mk(),
                          final_watermark=20_000)
    key = lambda kw: (kw[0], kw[1].start, kw[1].end,  # noqa: E731
                      tuple(kw[1].agg_values))
    assert sorted(map(key, out_s)) == sorted(map(key, out_r))


def test_kafka_run_with_shaper_drains_at_loop_end():
    from scotty_tpu.connectors.base import (
        AscendingWatermarks,
        KeyedScottyWindowOperator,
    )
    from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator

    records = chaos.make_records(seed=2, n=120, keys=3, period_ms=50)
    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=1000,
        watermark_policy=AscendingWatermarks())
    k = KafkaScottyWindowOperator(operator=op)
    got = []
    n = k.run(records, got.append,
              shaper=ShaperConfig(batch_size=16, slack_ms=200))
    assert n == 120
    got += op.process_watermark(100_000)

    op2 = KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=1000,
        watermark_policy=AscendingWatermarks())
    ref = []
    KafkaScottyWindowOperator(operator=op2).run(records, ref.append)
    ref += op2.process_watermark(100_000)
    key = lambda kw: (kw[0], kw[1].start, kw[1].end,  # noqa: E731
                      tuple(kw[1].agg_values))
    assert sorted(map(key, got)) == sorted(map(key, ref))


def test_asyncio_run_with_shaper_drains_at_source_end():
    import asyncio

    from scotty_tpu.connectors.asyncio_connector import run_keyed_async
    from scotty_tpu.connectors.base import (
        AscendingWatermarks,
        KeyedScottyWindowOperator,
    )

    vals, ts = _bounded_ooo_records(5, 90)
    recs = [("k", float(v), int(t)) for v, t in zip(vals, ts)]

    async def source():
        for r in recs:
            yield r

    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(Time, 1000)],
        aggregations=[SumAggregation()], allowed_lateness=1000,
        watermark_policy=AscendingWatermarks())
    got = []
    asyncio.run(run_keyed_async(
        source(), op, got.append,
        shaper=ShaperConfig(batch_size=16, slack_ms=1000)))
    got += op.process_watermark(20_000)
    total = sum(w.agg_values[0] for _, w in got)
    assert total == float(vals.sum())


def test_shaper_telemetry_counters_and_flight_events():
    from scotty_tpu import obs as obs_mod

    obs = obs_mod.Observability(flight=obs_mod.FlightRecorder(256))
    op = TpuWindowOperator(config=SMALL, obs=obs,
                           shaper=ShaperConfig(slack_ms=500))
    op.add_window_assigner(TumblingWindow(Time, 1000))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(4000)
    vals, ts = _bounded_ooo_records(6, 300)
    op.process_elements(vals.astype(np.float32), ts)
    op.process_watermark_arrays(int(ts.max()) + 5000)
    op.check_overflow()
    snap = obs.snapshot()
    assert snap["shaper_flushes"] >= 1
    assert snap["shaper_reordered_tuples"] == count_reordered(ts, None)
    assert snap["shaper_held_tuples"] == 0               # drained
    assert snap["shaper_fill_ratio_count"] >= 1
    kinds = {e["kind"] for e in obs.flight.events()}
    assert "shaper_flush" in kinds
    assert "shaper_held" in kinds


# ---------------------------------------------------------------------------
# CI gates + bench wiring
# ---------------------------------------------------------------------------


def test_obs_diff_gates_shaper_counters(tmp_path):
    import json

    from scotty_tpu.obs.diff import DEFAULT_THRESHOLDS, diff_exports

    for name in ("shaper_slack_overflows", "shaper_held_tuples",
                 "shaper_reordered_tuples"):
        assert name in DEFAULT_THRESHOLDS["metrics"]
    base = tmp_path / "base.json"
    cand = tmp_path / "cand.json"
    row = {"name": "cell", "windows": "w", "engine": "e",
           "aggregation": "sum", "tuples_per_sec": 100.0}
    base.write_text(json.dumps([row]))
    cand.write_text(json.dumps([dict(row, shaper_slack_overflows=2)]))
    findings = diff_exports(str(base), str(cand))
    bad = [f for f in findings if f["status"] == "regressed"]
    assert any(f["metric"] == "shaper_slack_overflows" for f in bad)


def test_shaped_ooo_runner_cell_smoke():
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_shaped_ooo_cell

    cfg = BenchmarkConfig(
        name="t", throughput=60_000, runtime_s=1,
        window_configurations=["Tumbling(1000)"],
        configurations=["ShapedOOO"], agg_functions=["sum"],
        batch_size=1 << 10, capacity=1 << 13, max_lateness=1000,
        watermark_period_ms=1000, seed=1)
    res = run_shaped_ooo_cell(cfg, "Tumbling(1000)", "sum")
    assert res.tuples_per_sec > 0
    assert res.shaper_reordered > 0


def test_ooo_external_config_parses():
    import os

    from scotty_tpu.bench.harness import BenchmarkConfig

    path = os.path.join(os.path.dirname(__file__), "..", "scotty_tpu",
                        "bench", "configurations", "ooo_external.json")
    cfg = BenchmarkConfig.from_json(path)
    assert cfg.configurations == ["ShapedOOO"]
    assert cfg.batch_size > 0


def test_micro_time_phase_drains_before_timing():
    from scotty_tpu.bench.micro import _time_phase

    calls = []
    r = _time_phase(lambda: calls.append("fn"),
                    lambda: calls.append("sync"), iters=3,
                    drain=lambda: calls.append("drain"))
    # the drain retires the queue BETWEEN warmup-sync and the idle-queue
    # sync measurement, so queued prior work can't be misattributed
    i_drain = calls.index("drain")
    assert calls[i_drain - 1] == "sync"
    assert calls[i_drain + 1] == "sync"
    assert r["iters"] == 3


# ---------------------------------------------------------------------------
# review hardening: keyed rounds end-to-end, geometry guard, checkpoints
# ---------------------------------------------------------------------------


def test_shape_device_round_end_to_end_matches_host_pack():
    import jax

    from scotty_tpu.engine.host_ingest import KeyedHostFeed
    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    K, Bk = 4, 64

    def mk():
        op = KeyedTpuWindowOperator(K, config=EngineConfig(
            capacity=1 << 10, batch_size=Bk, min_trigger_pad=32))
        op.add_window_assigner(TumblingWindow(Time, 1000))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(1000)
        return op

    rng = chaos.rng_of(2)
    N = K * Bk // 2
    keys = rng.integers(0, K, size=N).astype(np.int64)
    ts_sorted = np.sort(rng.integers(0, 4000, size=N)).astype(np.int64)
    vals = rng.integers(0, 100, size=N).astype(np.float32)
    perm = rng.permutation(N)            # the shaped arm gets DISORDER

    op_sh = mk()
    shaper = StreamShaper(op_sh)
    shaper.shape_device_round(jax.device_put(keys[perm]),
                              jax.device_put(vals[perm]),
                              jax.device_put(ts_sorted[perm]),
                              int(ts_sorted[0]), int(ts_sorted[-1]))
    op_ref = mk()
    KeyedHostFeed(op_ref).feed(keys, vals, ts_sorted)

    ws_a, we_a, cnt_a, low_a = op_sh.process_watermark_arrays(6000)
    ws_b, we_b, cnt_b, low_b = op_ref.process_watermark_arrays(6000)
    assert (np.asarray(ws_a) == np.asarray(ws_b)).all()
    assert (np.asarray(cnt_a) == np.asarray(cnt_b)).all()
    assert (np.asarray(low_a[0]) == np.asarray(low_b[0])).all()
    op_sh.check_overflow()


def test_shape_device_round_row_overflow_raises_at_keyed_drain():
    import jax

    from scotty_tpu.parallel.keyed import KeyedTpuWindowOperator

    K, Bk = 2, 8
    op = KeyedTpuWindowOperator(K, config=EngineConfig(
        capacity=1 << 8, batch_size=Bk, min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(SumAggregation())
    shaper = StreamShaper(op)
    N = 3 * Bk                            # key 0 holds 3x the round size
    keys = np.zeros(N, np.int64)
    ts = np.arange(N, dtype=np.int64)
    vals = np.ones(N, np.float32)
    shaper.shape_device_round(jax.device_put(keys), jax.device_put(vals),
                              jax.device_put(ts), 0, N - 1)
    with pytest.raises(ShaperOverflow, match="keyed round"):
        op.check_overflow()


def test_shaped_ooo_cell_rejects_mis_sized_geometry():
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_shaped_ooo_cell

    # span collapses to ~1 event-ms per batch -> the late fraction can
    # never fit the residue lanes; the cell must refuse up front instead
    # of dying in ShaperOverflow at the final drain
    cfg = BenchmarkConfig(
        name="bad", throughput=4_000_000, runtime_s=1,
        window_configurations=["Tumbling(1000)"],
        configurations=["ShapedOOO"], agg_functions=["sum"],
        batch_size=1 << 10, capacity=1 << 13, max_lateness=1000,
        watermark_period_ms=1000, seed=1)
    with pytest.raises(ValueError, match="ShapedOOO geometry"):
        run_shaped_ooo_cell(cfg, "Tumbling(1000)", "sum")


def test_checkpoint_flushes_held_shaper_records(tmp_path):
    from scotty_tpu.utils import checkpoint as ck

    def mk(shaper=None):
        op = TpuWindowOperator(config=SMALL, shaper=shaper)
        op.add_window_assigner(TumblingWindow(Time, 1000))
        op.add_aggregation(SumAggregation())
        op.set_max_lateness(2000)
        return op

    vals, ts = _bounded_ooo_records(8, 100)
    op = mk(ShaperConfig(slack_ms=10_000))    # slack holds EVERYTHING
    op.process_elements(vals.astype(np.float32), ts)
    assert op.shaper.held > 0
    ck.save_engine_operator(op, str(tmp_path / "ck"))
    assert op.shaper.held == 0                # flushed INTO the snapshot

    restored = mk()
    ck.restore_engine_operator(restored, str(tmp_path / "ck"))
    ref = mk()
    ref.process_elements(np.sort(ts).astype(np.float32) * 0
                         + vals[np.argsort(ts, kind="stable")]
                         .astype(np.float32), np.sort(ts))
    wm = int(ts.max()) + 3000
    got = _windows_dict(*restored.process_watermark_arrays(wm))
    exp = _windows_dict(*ref.process_watermark_arrays(wm))
    assert got == exp                         # nothing skipped


def test_keyed_connector_save_persists_shaper_results(tmp_path):
    from scotty_tpu.connectors.base import (
        AscendingWatermarks,
        KeyedScottyWindowOperator,
    )

    def mk(shaper=None):
        return KeyedScottyWindowOperator(
            windows=[TumblingWindow(Time, 1000)],
            aggregations=[SumAggregation()], allowed_lateness=1000,
            watermark_policy=AscendingWatermarks(), shaper=shaper)

    op = mk(ShaperConfig(batch_size=512, slack_ms=0))  # holds under 512
    for i in range(40):
        op.process_element("k", 1.0, i * 100)
    assert op._shaper.held == 40
    op.save(str(tmp_path / "snap"))
    assert op._shaper.held == 0               # drained into the snapshot

    restored = mk()                           # no shaper attached
    restored.restore(str(tmp_path / "snap"))
    out = restored.process_watermark(100_000)
    # every record (and every window the save-drain emitted) is delivered
    total = sum(w.agg_values[0] for _, w in out)
    assert total == 40.0
