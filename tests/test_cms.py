"""Count-min sketch aggregation (ISSUE 10 satellite, ROADMAP item 5):
fixed [depth·width] sum-combine partial riding the sparse-lift seam —
device bucketing bit-matches the scalar-face host oracle, the estimate
obeys the CMS error bound against exact counts, and the multi-cell lift
is rejected on the one-hot paths that cannot broadcast it."""

import numpy as np
import pytest

from scotty_tpu import (
    CountMinSketchAggregation,
    SessionWindow,
    SlicingWindowOperator,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator, UnsupportedOnDevice

Time = WindowMeasure.Time
Count = WindowMeasure.Count
CFG = EngineConfig(capacity=256, annex_capacity=32, batch_size=256,
                   min_trigger_pad=32)


def _heavy_stream(seed=7, n=3000, heavy=42.0, p_heavy=0.3, t_hi=1000):
    rng = np.random.default_rng(seed)
    vals = np.where(rng.random(n) < p_heavy, heavy,
                    rng.integers(0, 500, size=n)).astype(np.float64)
    ts = np.sort(rng.integers(0, t_hi, size=n))
    return vals, ts


def test_cms_validates_parameters():
    with pytest.raises(ValueError):
        CountMinSketchAggregation(1.0, depth=0)
    with pytest.raises(ValueError):
        CountMinSketchAggregation(1.0, width=100)     # not a power of two


def test_cms_scalar_face_error_bound():
    """est >= exact always (one-sided), and est - exact <= 2N/width per
    row on this concrete stream — the classic CMS guarantee, checked
    deterministically for the fixed salts."""
    agg = CountMinSketchAggregation(42.0, depth=4, width=256)
    vals, _ = _heavy_stream()
    part = [0] * (agg.depth * agg.width)
    for v in vals:
        part = agg.lift_and_combine(part, float(v))
    exact = int((vals == 42.0).sum())
    est = agg.lower(part)
    assert est >= exact
    assert est - exact <= 2 * len(vals) / 256


def test_cms_device_matches_host_oracle_through_engine():
    vals, ts = _heavy_stream()
    agg_args = dict(depth=4, width=256)
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(TumblingWindow(Time, 250))
    op.add_aggregation(CountMinSketchAggregation(42.0, **agg_args))
    op.process_elements(vals, ts)
    got = [w for w in op.process_watermark(1001) if w.has_value()]

    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 250))
    sim.add_aggregation(CountMinSketchAggregation(42.0, **agg_args))
    for v, t in zip(vals, ts):
        sim.process_element(float(v), int(t))
    want = [w for w in sim.process_watermark(1001) if w.has_value()]
    assert len(got) == len(want) == 4
    for a, b in zip(want, got):
        exact = int(((vals == 42.0) & (ts >= a.get_start())
                     & (ts < a.get_end())).sum())
        n_win = int(((ts >= a.get_start()) & (ts < a.get_end())).sum())
        est_h = float(a.get_agg_values()[0])
        est_d = float(b.get_agg_values()[0])
        assert est_h == est_d            # bit-identical bucketing
        assert exact <= est_d <= exact + 2 * n_win / 256


def test_cms_out_of_order_annex_path():
    """Late tuples fold through the annex's scatter-combine — the
    multi-cell broadcast must survive the covered/annex split too."""
    agg = CountMinSketchAggregation(7.0, depth=2, width=128)
    rng = np.random.default_rng(3)
    n = 600
    vals = np.where(rng.random(n) < 0.2, 7.0,
                    rng.integers(0, 100, size=n)).astype(np.float64)
    ts = rng.integers(0, 500, size=n).astype(np.int64)
    # bounded disorder within max_lateness
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(CountMinSketchAggregation(7.0, depth=2, width=128))
    op.set_max_lateness(1000)
    order = np.argsort(ts, kind="stable")
    # feed sorted batches but interleave one displaced late batch
    op.process_elements(vals[order][:500], ts[order][:500])
    op.process_elements(vals[order][500:], ts[order][500:])
    got = [w for w in op.process_watermark(501) if w.has_value()]

    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 100))
    sim.add_aggregation(CountMinSketchAggregation(7.0, depth=2, width=128))
    sim.set_max_lateness(1000)
    for v, t in zip(vals[order], ts[order]):
        sim.process_element(float(v), int(t))
    want = [w for w in sim.process_watermark(501) if w.has_value()]
    assert len(got) == len(want)
    for a, b in zip(want, got):
        assert float(a.get_agg_values()[0]) == float(b.get_agg_values()[0])


def test_cms_through_keyed_operator():
    """The keyed path (ISSUE 10 wiring): per-key CMS partials through the
    [K, ...] batched kernels match per-key scalar-face oracles."""
    from scotty_tpu.parallel import KeyedTpuWindowOperator

    rng = np.random.default_rng(5)
    K, n = 4, 1200
    keys = rng.integers(0, K, size=n)
    vals = np.where(rng.random(n) < 0.25, 9.0,
                    rng.integers(0, 200, size=n)).astype(np.float64)
    ts = np.sort(rng.integers(0, 400, size=n))
    op = KeyedTpuWindowOperator(
        n_keys=K, config=EngineConfig(capacity=1 << 10, batch_size=32,
                                      annex_capacity=128,
                                      min_trigger_pad=32))
    op.add_window_assigner(TumblingWindow(Time, 100))
    op.add_aggregation(CountMinSketchAggregation(9.0, depth=2, width=128))
    op.process_keyed_elements(keys, vals, ts)
    got = op.process_watermark(401)
    by_key = {}
    for k, w in got:
        by_key.setdefault(k, []).append(w)
    for k in range(K):
        agg = CountMinSketchAggregation(9.0, depth=2, width=128)
        sim = SlicingWindowOperator()
        sim.add_window_assigner(TumblingWindow(Time, 100))
        sim.add_aggregation(agg)
        m = keys == k
        for v, t in zip(vals[m], ts[m]):
            sim.process_element(float(v), int(t))
        want = [w for w in sim.process_watermark(401) if w.has_value()]
        assert len(by_key.get(k, [])) == len(want), k
        for a, b in zip(want, by_key[k]):
            assert float(a.get_agg_values()[0]) \
                == float(b.get_agg_values()[0]), (k, a.get_start())


def test_cms_through_keyed_aligned_pipeline():
    """The fused keyed pipeline now takes sparse lifts (the flat scatter
    fold): CMS estimates bit-match the scalar face on the materialized
    stream."""
    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    agg = CountMinSketchAggregation(2500.0, depth=2, width=128)
    p = KeyedAlignedPipeline(
        [TumblingWindow(Time, 100)], [agg], n_keys=8,
        config=EngineConfig(capacity=1 << 10, batch_size=32,
                            annex_capacity=8, min_trigger_pad=32),
        throughput=8 * 2000, wm_period_ms=100, max_lateness=100, seed=3,
        gc_every=4)
    p.reset()
    for i in range(3):
        out = p.run(1)[0]
        for kk in (0, 7):
            vals, _ts = p.materialize_interval(i, kk)
            rows = p.lowered_results_for_key(out, kk)
            assert rows
            for (s, e, c, v) in rows:
                part = [0] * (agg.depth * agg.width)
                for val in vals:
                    part = agg.lift_and_combine(part, float(val))
                assert float(v[0]) == agg.lower(part), (i, kk, s, e)
    p.check_overflow()


def test_cms_rejected_on_one_hot_paths():
    """Sessions (and count/context) densify one column per lane — the
    multi-cell lift must be refused loudly, not mis-bucketed."""
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(SessionWindow(Time, 100))
    op.add_aggregation(CountMinSketchAggregation(1.0, depth=2, width=64))
    with pytest.raises(UnsupportedOnDevice, match="time-grid"):
        op.process_element(1.0, 10)
    op2 = TpuWindowOperator(config=CFG)
    op2.add_window_assigner(TumblingWindow(Count, 10))
    op2.add_aggregation(CountMinSketchAggregation(1.0, depth=2, width=64))
    with pytest.raises(UnsupportedOnDevice, match="time-grid"):
        op2.process_element(1.0, 10)
    # host simulator remains the session/count fallback
    sim = SlicingWindowOperator()
    sim.add_window_assigner(SessionWindow(Time, 100))
    sim.add_aggregation(CountMinSketchAggregation(1.0, depth=2, width=64))
    sim.process_element(1.0, 10)
    out = [w for w in sim.process_watermark(500) if w.has_value()]
    assert len(out) == 1 and float(out[0].get_agg_values()[0]) == 1.0


def test_cms_alongside_dense_aggs():
    """Mixed registration: CMS + sum through one engine spec (the
    partials tuple mixes multi-cell sparse and dense widths)."""
    vals, ts = _heavy_stream(n=800)
    op = TpuWindowOperator(config=CFG)
    op.add_window_assigner(TumblingWindow(Time, 500))
    op.add_aggregation(SumAggregation())
    op.add_aggregation(CountMinSketchAggregation(42.0, depth=2,
                                                 width=128))
    op.process_elements(vals, ts)
    got = [w for w in op.process_watermark(1001) if w.has_value()]
    assert len(got) == 2
    for w in got:
        m = (ts >= w.get_start()) & (ts < w.get_end())
        assert float(w.get_agg_values()[0]) == pytest.approx(
            float(vals[m].sum()), rel=1e-6)
        exact = int((vals[m] == 42.0).sum())
        assert w.get_agg_values()[1] >= exact
