"""Chaos differential suite — overflow policies (ISSUE 3 tentpole).

Seeded burst overload (resilience.chaos) against a deliberately tiny
engine, asserted against the host simulator oracle:

* ``FAIL`` (default) raises exactly as the seed did, now counting the
  ``overflows`` metric on BOTH raise paths (buffer overflow + the session
  emission-buffer exceed — the ISSUE 3 satellite).
* ``SHED`` completes; the shed counts match exactly and the engine's
  results equal an oracle replay of precisely the surviving tuples.
* ``GROW`` completes with results bit-identical to a run pre-sized at the
  grown capacity — for the host-fed operator AND a fused pipeline grown
  mid-stream through the checkpoint pytree machinery.

All chaos is a pure function of its seed: CPU-deterministic, tier-1 speed.
"""

import numpy as np
import pytest

from scotty_tpu import (
    SessionWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator, UnsupportedOnDevice
from scotty_tpu.obs import Observability
from scotty_tpu.resilience import burst, grow_engine_config
from scotty_tpu.simulator import SlicingWindowOperator

Time, Count = WindowMeasure.Time, WindowMeasure.Count

#: burst: 512 tuples over [0, 5000) ms on a 10 ms tumbling grid → ~500
#: slices against capacity 32 — hard overload. Values are small integers
#: (exact in float32), so sums are association-independent and results
#: compare bit-for-bit across capacities and against the oracle.
BURST_VALS, BURST_TS = burst(seed=0, n=512, t0=0, t1=5000)
WM = 5000


def make_op(policy="fail", capacity=32, max_capacity=0, obs=None):
    op = TpuWindowOperator(
        config=EngineConfig(capacity=capacity, batch_size=64,
                            annex_capacity=8, min_trigger_pad=32,
                            overflow_policy=policy,
                            max_capacity=max_capacity),
        obs=obs)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    return op


def run_burst(op):
    op.process_elements(BURST_VALS, BURST_TS)
    ws, we, cnt, low = op.process_watermark_arrays(WM)
    op.check_overflow()
    return [(int(a), int(b), float(v)) for a, b, c, v in
            zip(ws, we, cnt, low[0]) if c > 0]


def oracle_rows(vals, ts):
    sim = SlicingWindowOperator()
    sim.add_window_assigner(TumblingWindow(Time, 10))
    sim.add_aggregation(SumAggregation())
    sim.set_max_lateness(10_000)
    for v, t in zip(vals, ts):
        sim.process_element(float(v), int(t))
    return [(w.start, w.end, float(w.agg_values[0]))
            for w in sim.process_watermark(WM) if w.has_value()]


def test_fail_policy_raises_exactly_as_before_and_counts_overflow():
    obs = Observability()
    op = make_op("fail", obs=obs)
    op.process_elements(BURST_VALS, BURST_TS)
    with pytest.raises(RuntimeError, match="slice/session buffer overflow"):
        op.process_watermark_arrays(WM)
    assert obs.registry.snapshot()["overflows"] == 1


def test_session_emission_buffer_exceed_counts_overflow():
    """The second raise path (operator.py _fetch_sessions): exceeding the
    session emission buffer must increment ``overflows`` and name the
    actionable knobs. The buffer bound is host-checked against
    ``_emit_cap``, which is lowered after build to hit the path without
    sweeping >1024 sessions through a tier-1 test."""
    obs = Observability()
    op = TpuWindowOperator(
        config=EngineConfig(capacity=256, batch_size=64, annex_capacity=16,
                            min_trigger_pad=32), obs=obs)
    op.add_window_assigner(SessionWindow(Time, 5))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(1000)
    ts = np.arange(8, dtype=np.int64) * 20          # 8 gap-separated sessions
    op.process_elements(np.ones(8, np.float32), ts)
    op._flush()
    assert op._built
    op._emit_cap = 2
    with pytest.raises(RuntimeError, match="emission buffer"):
        op.process_watermark_arrays(1000)
    assert obs.registry.snapshot()["overflows"] == 1


def test_shed_completes_and_matches_surviving_tuple_oracle_replay():
    obs = Observability()
    op = make_op("shed", obs=obs)
    shed = []
    op.shed_callback = lambda v, t: shed.append((v.copy(), t.copy()))
    rows = run_burst(op)

    n_shed = sum(v.shape[0] for v, _ in shed)
    assert n_shed > 0
    snap = obs.registry.snapshot()
    assert snap["resilience_shed_tuples"] == n_shed
    assert "overflows" not in snap or snap["overflows"] == 0
    # exact in-jit auditability: drops ride DeviceMetrics too
    assert op.device_metrics()["device_dropped_tuples"] == n_shed

    # survivors = offered multiset minus the shed multiset, in offer order
    budget: dict = {}
    for v, t in shed:
        for vv, tt in zip(v, t):
            k = (float(vv), int(tt))
            budget[k] = budget.get(k, 0) + 1
    surv_v, surv_t = [], []
    for vv, tt in zip(BURST_VALS, BURST_TS):
        k = (float(vv), int(tt))
        if budget.get(k, 0) > 0:
            budget[k] -= 1
            continue
        surv_v.append(vv)
        surv_t.append(tt)
    assert len(surv_v) + n_shed == BURST_VALS.shape[0]
    assert rows == oracle_rows(surv_v, surv_t)


def test_shed_is_deterministic():
    def one():
        op = make_op("shed")
        shed = []
        op.shed_callback = lambda v, t: shed.append((v.tolist(), t.tolist()))
        rows = run_burst(op)
        return rows, shed

    assert one() == one()


def test_grow_completes_bit_identical_to_presized_run():
    obs = Observability()
    op = make_op("grow", max_capacity=4096, obs=obs)
    rows = run_burst(op)

    snap = obs.registry.snapshot()
    assert snap["resilience_grow_events"] >= 1
    assert op.config.capacity > 32

    ref = TpuWindowOperator(config=EngineConfig(
        capacity=op.config.capacity, batch_size=64,
        annex_capacity=op.config.annex_capacity, min_trigger_pad=32))
    ref.add_window_assigner(TumblingWindow(Time, 10))
    ref.add_aggregation(SumAggregation())
    ref.set_max_lateness(10_000)
    assert rows == run_burst(ref)
    # nothing was dropped on the way
    assert "resilience_shed_tuples" not in snap


def test_grow_respects_max_capacity():
    op = make_op("grow", max_capacity=64)      # one doubling only
    with pytest.raises(RuntimeError, match="max_capacity"):
        run_burst(op)


def test_grow_preserves_mid_stream_watermark_state():
    """Growth between two watermarks must carry the host clock mirrors:
    the second watermark's trigger range continues from the first."""
    op = make_op("grow", max_capacity=4096)
    half = BURST_TS.shape[0] // 2
    op.process_elements(BURST_VALS[:half], BURST_TS[:half])
    ws1, we1, cnt1, low1 = op.process_watermark_arrays(2500)
    op.process_elements(BURST_VALS[half:], BURST_TS[half:])
    ws2, we2, cnt2, low2 = op.process_watermark_arrays(WM)
    op.check_overflow()

    ref = make_op("fail", capacity=4096)
    ref.process_elements(BURST_VALS[:half], BURST_TS[:half])
    r1 = ref.process_watermark_arrays(2500)
    ref.process_elements(BURST_VALS[half:], BURST_TS[half:])
    r2 = ref.process_watermark_arrays(WM)
    assert np.array_equal(ws1, r1[0]) and np.array_equal(cnt1, r1[2])
    assert np.array_equal(ws2, r2[0]) and np.array_equal(cnt2, r2[2])
    assert all(np.array_equal(a, b) for a, b in zip(low1, r1[3]))
    assert all(np.array_equal(a, b) for a, b in zip(low2, r2[3]))


def test_policy_validation():
    with pytest.raises(ValueError, match="unknown overflow_policy"):
        EngineConfig(overflow_policy="bogus")
    # unsupported workload classes reject policies explicitly at build
    op = TpuWindowOperator(config=EngineConfig(
        capacity=256, batch_size=64, min_trigger_pad=32,
        overflow_policy="shed"))
    op.add_window_assigner(TumblingWindow(Count, 7))
    op.add_aggregation(SumAggregation())
    with pytest.raises(UnsupportedOnDevice, match="overflow_policy"):
        op.process_elements(np.ones(4, np.float32),
                            np.arange(4, dtype=np.int64))


def test_grow_engine_config_doubles_and_bounds():
    cfg = EngineConfig(capacity=32, annex_capacity=8, max_capacity=128)
    g = grow_engine_config(cfg)
    assert g.capacity == 64 and g.annex_capacity == 16
    g2 = grow_engine_config(g)
    assert g2.capacity == 128
    with pytest.raises(RuntimeError, match="max_capacity"):
        grow_engine_config(g2)


def test_grow_default_bound_anchors_to_original_capacity():
    """max_capacity=0 means 8× the ORIGINAL capacity — the implicit bound
    must not drift upward with each doubling (that would grow forever
    under sustained overload, the OOM spiral the bound exists to stop)."""
    cfg = EngineConfig(capacity=32, annex_capacity=8)     # bound = 256
    for expect in (64, 128, 256):
        cfg = grow_engine_config(cfg)
        assert cfg.capacity == expect
    with pytest.raises(RuntimeError, match="max_capacity=256"):
        grow_engine_config(cfg)


def test_restore_refreshes_shed_admission_mirror(tmp_path):
    """Supervisor-restart path: a restored operator's admission mirror
    must reflect the checkpointed device occupancy — a zeroed mirror
    would admit past capacity and die on the fatal overflow SHED exists
    to prevent."""
    from scotty_tpu.utils.checkpoint import (restore_engine_operator,
                                             save_engine_operator)

    op = make_op("shed", capacity=32)
    shed0 = []
    op.shed_callback = lambda v, t: shed0.append(t)
    # ~25 distinct 10ms grid slices, under capacity: nothing shed yet
    ts1 = np.arange(25, dtype=np.int64) * 10
    op.process_elements(np.ones(25, np.float32), ts1)
    op._flush()
    assert not shed0
    save_engine_operator(op, str(tmp_path / "op"))

    op2 = make_op("shed", capacity=32)
    restore_engine_operator(op2, str(tmp_path / "op"))
    shed = []
    op2.shed_callback = lambda v, t: shed.append(t)
    ts2 = 250 + np.arange(25, dtype=np.int64) * 10      # 25 MORE new slices
    op2.process_elements(np.ones(25, np.float32), ts2)
    op2.process_watermark_arrays(1000)
    op2.check_overflow()                                # no fatal overflow
    assert shed                                         # mirror was live


def test_pipeline_grow_bit_identical_to_presized(tmp_path):
    """GROW on a fused pipeline: enforce_overflow_policy at the drain
    points doubles capacity through the checkpoint pytree machinery
    BEFORE the overflow flag can rise; the full interval stream is
    bit-identical to a run pre-sized at the final capacity."""
    import dataclasses

    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def make(config):
        return AlignedStreamPipeline(
            [TumblingWindow(Time, 50)], [SumAggregation()], config=config,
            throughput=20_000, wm_period_ms=100, max_lateness=100, seed=5,
            gc_every=10 ** 9, value_scale=1024.0)

    cfg = EngineConfig(capacity=64, batch_size=256, annex_capacity=8,
                       min_trigger_pad=32, overflow_policy="grow",
                       max_capacity=1024)
    obs = Observability()
    p = make(cfg)
    p.set_observability(obs)
    N = 40                                  # 80 slices offered vs capacity 64
    rows = []
    for _ in range(N // 4):
        rows.extend(p.lowered_results(o) for o in p.run(4))
        p = p.enforce_overflow_policy(factory=make)
    assert p.config.capacity > 64
    assert obs.registry.snapshot()["resilience_grow_events"] >= 1

    big = dataclasses.replace(cfg, capacity=p.config.capacity,
                              annex_capacity=p.config.annex_capacity,
                              overflow_policy="fail")
    q = make(big)
    rows_q = [q.lowered_results(o) for o in q.run(N)]
    q.check_overflow()
    assert rows == rows_q

    # the same load under FAIL at the original capacity overflows —
    # the exact seed behavior GROW is proven to prevent
    pf = make(dataclasses.replace(cfg, overflow_policy="fail"))
    pf.run(N)
    with pytest.raises(RuntimeError, match="overflow"):
        pf.check_overflow()


def test_device_resident_ingest_rejects_policies():
    op = make_op("shed", capacity=256)
    import jax

    ts = jax.numpy.arange(64, dtype=jax.numpy.int64)
    vals = jax.numpy.ones((64,), jax.numpy.float32)
    with pytest.raises(UnsupportedOnDevice, match="host-visible"):
        op.ingest_device_batch(vals, ts, 0, 63)
