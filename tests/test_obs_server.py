"""Live /metrics · /vars · /healthz endpoint (ISSUE 4): serving a real
Observability, answering correctly on a LIVE connector pipeline, and the
opt-in ``serve_port`` wiring on the kafka/asyncio run loops."""

import asyncio
import json
import urllib.error
import urllib.request

from scotty_tpu.connectors.base import (
    KeyedScottyWindowOperator,
    PeriodicWatermarks,
)
from scotty_tpu.connectors.kafka import KafkaScottyWindowOperator
from scotty_tpu.obs import HealthPolicy, Observability
from scotty_tpu.obs.server import serve
from scotty_tpu.resilience import make_records


def _get(port, path):
    try:
        r = urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                   timeout=5)
        return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


def test_serve_metrics_vars_healthz_and_404():
    obs = Observability()
    obs.counter("ingest_tuples").inc(42)
    obs.gauge("watermark_lag_ms").set(10.0)
    obs.histogram("emit_latency_ms").observe(3.0)
    with obs.serve(port=0) as srv:
        code, text = _get(srv.port, "/metrics")
        assert code == 200
        assert "# TYPE scotty_ingest_tuples counter" in text
        assert "scotty_ingest_tuples 42.0" in text

        code, text = _get(srv.port, "/vars")
        assert code == 200
        body = json.loads(text)
        assert body["metrics"]["ingest_tuples"] == 42.0

        code, text = _get(srv.port, "/healthz")
        assert code == 200
        assert json.loads(text)["healthy"] is True

        code, _ = _get(srv.port, "/nope")
        assert code == 404
    # every probe was itself counted (the health_* contract)
    assert obs.snapshot()["health_checks"] == 1


def test_healthz_http_codes_follow_the_lag_verdict():
    obs = Observability()
    obs.gauge("watermark_lag_ms").set(500.0)
    with obs.serve(port=0,
                   health=HealthPolicy(max_watermark_lag_ms=100)) as srv:
        code, text = _get(srv.port, "/healthz")
        assert code == 503
        v = json.loads(text)
        assert not v["healthy"]
        assert not v["checks"]["watermark_lag"]["ok"]
        obs.gauge("watermark_lag_ms").set(5.0)
        code, _ = _get(srv.port, "/healthz")
        assert code == 200
    assert obs.snapshot()["health_unhealthy"] == 1


def test_provider_server_answers_503_between_cells():
    """The bench runner serves ONE endpoint across cells via a provider;
    with no live cell it answers 503 instead of crashing."""
    live = {"obs": None}
    with serve(lambda: live["obs"], port=0) as srv:
        code, _ = _get(srv.port, "/metrics")
        assert code == 503
        live["obs"] = Observability()
        live["obs"].counter("ingest_tuples").inc(1)
        code, text = _get(srv.port, "/metrics")
        assert code == 200 and "scotty_ingest_tuples 1.0" in text


def test_kafka_run_serves_live_pipeline(tmp_path):
    """serve_port on the kafka run() loop: the endpoint answers while the
    connector pipeline is LIVE — a mid-stream record probes /metrics and
    /healthz from inside the consumer iterable — and the server is gone
    after run() returns."""
    obs = Observability()
    kop = KafkaScottyWindowOperator(
        operator=KeyedScottyWindowOperator(
            watermark_policy=PeriodicWatermarks(100), obs=obs))
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure

    kop.operator.add_window(TumblingWindow(WindowMeasure.Time, 200))
    kop.operator.add_aggregation(SumAggregation())
    records = make_records(seed=7, n=60, keys=2, period_ms=10)
    probes = []

    def consumer():
        for r in records[:40]:
            yield r
        # mid-stream: the loop is live, the server is up
        port = kop.obs_server.port
        probes.append(_get(port, "/metrics"))
        probes.append(_get(port, "/healthz"))
        for r in records[40:]:
            yield r

    out = []
    n = kop.run(consumer(), on_result=out.append, serve_port=0)
    assert n == len(records) and out
    assert kop.obs_server is None               # closed after the loop
    (m_code, m_text), (h_code, h_text) = probes
    assert m_code == 200
    assert "scotty_ingest_tuples 40.0" in m_text
    assert "scotty_watermarks" in m_text
    assert h_code == 200 and json.loads(h_text)["healthy"]


def test_run_loop_forwards_health_policy():
    """The run-loop wirings forward ``health=`` to serve(), so the
    watermark-lag check is configurable on a served connector loop —
    and the operator declares ``obs_server`` (None) even before any
    served run."""
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure

    obs = Observability()
    kop = KafkaScottyWindowOperator(
        operator=KeyedScottyWindowOperator(
            watermark_policy=PeriodicWatermarks(100), obs=obs))
    assert kop.operator.obs_server is None      # declared, not ad hoc
    kop.operator.add_window(TumblingWindow(WindowMeasure.Time, 200))
    kop.operator.add_aggregation(SumAggregation())
    obs.gauge("watermark_lag_ms").set(900.0)    # a badly lagging stream
    records = make_records(seed=3, n=20, keys=2, period_ms=10)
    probes = []

    def consumer():
        for r in records[:10]:
            yield r
        probes.append(_get(kop.obs_server.port, "/healthz"))
        for r in records[10:]:
            yield r

    kop.run(consumer(), on_result=lambda *_: None, serve_port=0,
            health=HealthPolicy(max_watermark_lag_ms=100))
    code, text = probes[0]
    assert code == 503
    assert not json.loads(text)["checks"]["watermark_lag"]["ok"]


def test_asyncio_run_serves_live_pipeline():
    """serve_port on run_keyed_async: probed mid-stream via the source
    (run_in_executor keeps the event loop honest), closed afterwards."""
    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.connectors.asyncio_connector import run_keyed_async

    obs = Observability()
    op = KeyedScottyWindowOperator(
        windows=[TumblingWindow(WindowMeasure.Time, 100)],
        aggregations=[SumAggregation()], obs=obs)
    probes = []

    async def source():
        loop = asyncio.get_running_loop()
        for t in range(0, 400, 10):
            if t == 200:
                port = op.obs_server.port
                probes.append(await loop.run_in_executor(
                    None, _get, port, "/healthz"))
            yield ("k", 1.0, t)

    out = []
    asyncio.run(run_keyed_async(source(), op, emit=out.append,
                                serve_port=0))
    assert out
    assert op.obs_server is None
    assert probes and probes[0][0] == 200
