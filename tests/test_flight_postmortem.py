"""Flight recorder + crash postmortems + health verdicts (ISSUE 4).

Chaos-driven coverage: under ``CrashInjector`` and ``StallingSource`` on
a ``ManualClock``, postmortem bundles are produced atomically, the
reconstructed timeline matches the oracle event order exactly, and the
``/healthz`` verdict flips unhealthy at the configured watermark-lag
threshold / on fresh stall-watchdog events.
"""

import json
import os

import numpy as np
import pytest

from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.operator import TpuWindowOperator
from scotty_tpu.engine.pipeline import AlignedStreamPipeline
from scotty_tpu.obs import (
    FLIGHT_DROPPED_EVENTS,
    FlightRecorder,
    HealthPolicy,
    Observability,
    write_postmortem,
)
from scotty_tpu.obs.flight import list_postmortems, read_postmortem
from scotty_tpu.obs.postmortem import analyze, postmortem_main
from scotty_tpu.obs.report import main as obs_main
from scotty_tpu.resilience import (
    ChaosError,
    CrashInjector,
    ManualClock,
    StallingSource,
    Supervisor,
    SupervisorGaveUp,
    burst,
    watchdog_source,
)

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 12, batch_size=256, annex_capacity=256,
                   min_trigger_pad=32)


# ---------------------------------------------------------------------------
# the ring itself
# ---------------------------------------------------------------------------


def test_ring_wraparound_keeps_newest_and_counts_dropped():
    clock = ManualClock()
    fl = FlightRecorder(capacity=8, clock=clock)
    for i in range(20):
        clock.advance(1.0)
        fl.record("mark", "m", i)
    ev = fl.events()
    assert len(ev) == 8
    assert [e["seq"] for e in ev] == list(range(12, 20))   # newest window
    assert [e["value"] for e in ev] == list(range(12, 20))
    assert ev[0]["t"] == 13.0                  # ManualClock drove the stamps
    assert fl.dropped == 12
    snap = fl.snapshot()
    assert snap["schema"].startswith("scotty_tpu.flight/")
    assert snap["dropped"] == 12 and snap["next_seq"] == 20


def test_observability_span_and_sample_feed_the_ring():
    obs = Observability(flight=FlightRecorder(capacity=64,
                                              clock=ManualClock()))
    with obs.span("drain"):
        obs.counter("ingest_tuples").inc(100)
    obs.gauge("slice_occupancy").set(0.25)
    obs.flight_sync(watermark=500)
    kinds = [(e["kind"], e["name"]) for e in obs.flight.events()]
    assert ("span_open", "drain") in kinds
    assert ("span_close", "drain") in kinds
    assert ("watermark", "watermark") in kinds
    assert ("counter", "ingest_tuples") in kinds
    assert ("gauge", "slice_occupancy") in kinds
    # spans still land in the SpanRecorder too
    assert obs.spans.summary()["drain"]["count"] == 1
    # delta semantics: a second unchanged sample records nothing new
    n = len(obs.flight.events())
    obs.flight_sample()
    assert len(obs.flight.events()) == n
    obs.counter("ingest_tuples").inc(7)
    obs.flight_sample()
    last = obs.flight.events()[-1]
    assert (last["kind"], last["value"]) == ("counter", 7.0)


def test_wraparound_drops_fold_into_registry_exactly_once():
    obs = Observability(flight=FlightRecorder(capacity=4,
                                              clock=ManualClock()))
    for i in range(10):
        obs.flight.record("mark", "m", i)
    obs.flight_sample()
    first = obs.snapshot()[FLIGHT_DROPPED_EVENTS]
    assert first >= 6                     # 10 recorded into 4 slots
    obs.flight_sample()                   # no new drops -> no re-fold
    assert obs.snapshot()[FLIGHT_DROPPED_EVENTS] == first


# ---------------------------------------------------------------------------
# bundles
# ---------------------------------------------------------------------------


def test_postmortem_bundle_atomic_roundtrip(tmp_path):
    obs = Observability(flight=FlightRecorder(capacity=16,
                                              clock=ManualClock()))
    obs.counter("ingest_tuples").inc(5)
    obs.flight_sample()
    d = str(tmp_path / "pm")
    p0 = write_postmortem(d, exception=RuntimeError("boom"), obs=obs,
                          config=CFG, checkpoint="ckpt-4", label="unit")
    p1 = write_postmortem(d, obs=obs)          # clean snapshot bundle
    assert os.path.basename(p0) == "postmortem-0.json"
    assert os.path.basename(p1) == "postmortem-1.json"
    # atomic commit: no temp residue next to the bundles
    assert not [f for f in os.listdir(d) if ".tmp." in f]
    assert list_postmortems(d) == [p0, p1]
    b = read_postmortem(p0)
    assert b["exception"]["type"] == "RuntimeError"
    assert b["config"]["capacity"] == CFG.capacity
    assert b["checkpoint"] == "ckpt-4"
    assert b["flight"]["events"]
    assert b["registry"]["ingest_tuples"] == 5
    # a clean snapshot bundle reads as no-failure; the CLI exits 0 on it
    assert analyze(read_postmortem(p1))["cause"] == "none"
    assert postmortem_main(p1, echo=lambda s: None) == 0
    with pytest.raises(ValueError, match="not a postmortem bundle"):
        bad = tmp_path / "x.json"
        bad.write_text("{}")
        read_postmortem(str(bad))


def pipeline_factory(config=None):
    return AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [SumAggregation()],
        config=config or CFG, throughput=20_000, wm_period_ms=100,
        max_lateness=100, seed=5, gc_every=10 ** 9, value_scale=1024.0)


def test_supervised_crash_bundle_timeline_matches_oracle_order(tmp_path):
    """A CrashInjector run yields a postmortem bundle whose reconstructed
    resilience timeline bit-matches the injected event sequence
    (checkpoints at 2 and 4, the crash at 5, nothing else), and the
    recovery completes with the post-restart events in oracle order."""
    obs = Observability(flight=FlightRecorder(capacity=256,
                                              clock=ManualClock()))
    sup = Supervisor(str(tmp_path / "ckpt"), clock=ManualClock(), obs=obs,
                     checkpoint_every=2, max_restarts=2, seed=9)
    crash = CrashInjector(at=5)
    rows = sup.run_pipeline(pipeline_factory, 8, fault=crash)
    assert crash.fired == 5

    bundles = list_postmortems(str(tmp_path / "ckpt"))
    assert len(bundles) == 1              # exactly one restart attempt
    b = read_postmortem(bundles[0])
    resil = [(e["kind"], e["name"], e["value"])
             for e in b["flight"]["events"]
             if e["kind"] in ("checkpoint", "restart", "restore",
                              "gave_up")]
    # the oracle event order of the injected chaos, bit-for-bit
    assert resil == [("checkpoint", "interval", 2.0),
                     ("checkpoint", "interval", 4.0),
                     ("restart", "ChaosError", 1.0)]
    assert b["exception"]["type"] == "ChaosError"
    assert b["checkpoint"] and b["checkpoint"].endswith("ckpt-4")
    assert b["config"]["capacity"] == CFG.capacity
    a = analyze(b)
    assert a["failed"] and a["cause"] == "crash"
    assert a["last_watermark_ms"] == 400.0     # last drained sync: ckpt-4
    assert a["checkpoint_history"][-1]["position"] == 4.0

    # the full post-recovery timeline continues in oracle order
    full = [(e["kind"], e["value"]) for e in obs.flight.events()
            if e["kind"] in ("checkpoint", "restart", "restore")]
    assert full == [("checkpoint", 2.0), ("checkpoint", 4.0),
                    ("restart", 1.0), ("restore", 0.0),
                    ("checkpoint", 6.0), ("checkpoint", 8.0)]
    # and recovery stayed bit-identical to an uninterrupted run
    ref = pipeline_factory()
    assert rows == [ref.lowered_results(o) for o in ref.run(8)]


def test_crash_loop_bundle_classifies_and_cli_exits_nonzero(tmp_path,
                                                            capsys):
    obs = Observability(flight=FlightRecorder(capacity=128,
                                              clock=ManualClock()))
    sup = Supervisor(str(tmp_path / "ckpt"), clock=ManualClock(), obs=obs,
                     checkpoint_every=2, max_restarts=1, seed=1)

    def always_crash(pos):
        raise ChaosError("permanent failure")

    with pytest.raises(SupervisorGaveUp):
        sup.run_pipeline(pipeline_factory, 8, fault=always_crash)

    bundles = list_postmortems(str(tmp_path / "ckpt"))
    assert bundles                        # every attempt + the give-up
    last = read_postmortem(bundles[-1])
    assert last["exception"]["type"] == "SupervisorGaveUp"
    assert last["exception"]["cause_type"] == "ChaosError"
    a = analyze(last)
    assert a["cause"] == "crash_loop"
    assert len(a["restart_history"]) >= 2       # restarts + gave_up events

    # the CLI: nonzero exit, cause named in the human report AND --json
    assert obs_main(["postmortem", bundles[-1]]) == 1
    out = capsys.readouterr().out
    assert "probable cause: crash_loop" in out
    assert obs_main(["postmortem", bundles[-1], "--json",
                     "--timeline"]) == 1
    parsed = json.loads(capsys.readouterr().out)
    assert parsed["cause"] == "crash_loop"
    assert parsed["timeline"]


def test_overflow_fail_path_dumps_bundle(tmp_path):
    """The overflow FAIL path dumps a bundle (obs.postmortem_dir armed)
    that classifies as ``overflow``."""
    vals, ts = burst(seed=0, n=512, t0=0, t1=5000)
    obs = Observability(flight=FlightRecorder(capacity=64,
                                              clock=ManualClock()),
                        postmortem_dir=str(tmp_path / "pm"))
    op = TpuWindowOperator(
        config=EngineConfig(capacity=32, batch_size=64, annex_capacity=8,
                            min_trigger_pad=32), obs=obs)
    op.add_window_assigner(TumblingWindow(Time, 10))
    op.add_aggregation(SumAggregation())
    op.set_max_lateness(10_000)
    op.process_elements(vals, ts)
    with pytest.raises(RuntimeError, match="overflow"):
        op.process_watermark_arrays(5000)
    bundles = list_postmortems(str(tmp_path / "pm"))
    assert len(bundles) == 1
    b = read_postmortem(bundles[0])
    assert analyze(b)["cause"] == "overflow"
    assert b["config"]["capacity"] == 32
    assert any(e["kind"] == "overflow" for e in b["flight"]["events"])
    assert postmortem_main(bundles[0], echo=lambda s: None) == 1


# ---------------------------------------------------------------------------
# health verdicts
# ---------------------------------------------------------------------------


def test_healthz_flips_unhealthy_at_watermark_lag_threshold():
    obs = Observability()
    policy = HealthPolicy(max_watermark_lag_ms=100)
    obs.gauge("watermark_lag_ms").set(60.0)
    v = policy.verdict(obs)
    assert v["healthy"] and v["checks"]["watermark_lag"]["ok"]
    obs.gauge("watermark_lag_ms").set(101.0)     # crosses the threshold
    v = policy.verdict(obs)
    assert not v["healthy"]
    assert not v["checks"]["watermark_lag"]["ok"]
    obs.gauge("watermark_lag_ms").set(0.0)       # caught up again
    assert policy.verdict(obs)["healthy"]
    snap = obs.snapshot()
    assert snap["health_checks"] == 3
    assert snap["health_unhealthy"] == 1


def test_stall_watchdog_flips_health_under_manual_clock():
    """StallingSource + watchdog_source on a ManualClock: the stall is
    flagged deterministically, lands in the flight ring, and the NEXT
    health probe is unhealthy (recovering on the one after)."""
    mc = ManualClock()
    obs = Observability(flight=FlightRecorder(capacity=32, clock=mc))
    policy = HealthPolicy()
    assert policy.verdict(obs)["healthy"]        # baseline probe

    src = StallingSource(list(range(8)), stall_at=[3], stall_s=5.0,
                         clock=mc)
    got = list(watchdog_source(src, stall_timeout_s=1.0, clock=mc,
                               obs=obs))
    assert got == list(range(8))                 # stream survived the stall
    snap = obs.snapshot()
    assert snap["resilience_stall_events"] == 1
    stalls = [e for e in obs.flight.events() if e["kind"] == "stall"]
    assert len(stalls) == 1 and stalls[0]["value"] == 5.0

    v = policy.verdict(obs)
    assert not v["healthy"]
    assert not v["checks"]["stall_watchdog"]["ok"]
    assert policy.verdict(obs)["healthy"]        # no NEW stalls since
    assert obs.snapshot()["health_unhealthy"] == 1
    # the unhealthy verdict itself is flight-recorded
    assert any(e["kind"] == "health" for e in obs.flight.events())


def test_pipeline_sync_samples_flight_with_zero_extra_syncs():
    """The drain-point contract: running a fused pipeline with a flight
    recorder attached lands watermark + counter/gauge samples in the
    ring via the EXISTING sync, and the postmortem occupancy trend is
    reconstructible from the gauge samples."""
    obs = Observability(flight=FlightRecorder(capacity=256,
                                              clock=ManualClock()))
    p = pipeline_factory()
    p.reset()
    p.set_observability(obs)
    for _ in range(3):
        p.run(2)
        p.sync()
    ev = obs.flight.events()
    wms = [e["value"] for e in ev if e["kind"] == "watermark"]
    assert wms == [200.0, 400.0, 600.0]
    assert any(e["kind"] == "gauge" and e["name"] == "slice_occupancy"
               for e in ev)
    assert any(e["kind"] == "counter" and e["name"] == "ingest_tuples"
               for e in ev)
