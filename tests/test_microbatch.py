"""Micro-batched streamed emission (ISSUE 15): ``run_streamed`` must
bit-match whole-interval ``run()`` on every fused pipeline + mesh, keep
the step loop clean under ``jax.transfer_guard("disallow")``, resume a
mid-interval checkpoint of the micro-batched carry bit-identically, and
keep the LatencyTracer conservation identity exact over the streamed
stamps."""

import numpy as np
import pytest

import scotty_tpu.obs as obs_mod
from scotty_tpu import (
    SessionWindow,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.engine import EngineConfig

Time = WindowMeasure.Time


def _leaves_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def _aligned(micro=4, **flags):
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    return AlignedStreamPipeline(
        [SlidingWindow(Time, 400, 100)], [SumAggregation()],
        config=EngineConfig(capacity=1 << 12, annex_capacity=256,
                            min_trigger_pad=32, micro_batch=micro,
                            **flags),
        throughput=2560, wm_period_ms=200, max_lateness=200, seed=3,
        gc_every=10 ** 9, value_scale=8.0)


def test_aligned_microbatch_bit_matches_whole_interval():
    """Same construction (micro_batch forces the per-(row, sub) keying
    on BOTH paths): M micro-dispatches + flush == the one-step run."""
    import jax

    ref = _aligned()
    r_ref = [jax.device_get(r) for r in ref.run(5)]
    ref.sync()
    mb = _aligned()
    r_mb = mb.run_streamed(5)
    _leaves_equal(r_ref, r_mb)
    mb.check_overflow()


def test_aligned_microbatch_ooo_late_fold_bit_matches():
    """The late fold rides micro-batch 0 (lax.cond on the micro index)
    — out-of-order streams bit-match too."""
    import jax

    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    def mk():
        return AlignedStreamPipeline(
            [SlidingWindow(Time, 400, 100)], [SumAggregation()],
            config=EngineConfig(capacity=1 << 12, annex_capacity=256,
                                min_trigger_pad=32, micro_batch=4),
            throughput=2560, wm_period_ms=200, max_lateness=200, seed=3,
            gc_every=10 ** 9, value_scale=8.0, out_of_order_pct=0.05)

    ref = mk()
    r_ref = [jax.device_get(r) for r in ref.run(4)]
    ref.sync()
    mb = mk()
    r_mb = mb.run_streamed(4)
    _leaves_equal(r_ref, r_mb)


def test_generic_pipeline_streamed_bit_matches():
    """StreamPipeline has no micro step — run_streamed degrades to
    per-interval streamed fetches of the SAME step."""
    import jax

    from scotty_tpu.engine.pipeline import StreamPipeline

    def mk():
        return StreamPipeline(
            [TumblingWindow(Time, 100)], [SumAggregation()],
            config=EngineConfig(capacity=1 << 12, annex_capacity=64,
                                min_trigger_pad=32),
            throughput=20_000, wm_period_ms=200, max_lateness=200,
            seed=1, sub_batch=1 << 10)

    a = mk()
    ra = [jax.device_get(r) for r in a.run(3)]
    a.sync()
    b = mk()
    rb = b.run_streamed(3)
    _leaves_equal(ra, rb)


def test_session_pipeline_streamed_bit_matches():
    import jax

    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    def mk():
        return SessionStreamPipeline(
            [SessionWindow(Time, 1000)], [SumAggregation()],
            config=EngineConfig(capacity=1 << 12, annex_capacity=8,
                                min_trigger_pad=32),
            throughput=4000, wm_period_ms=1000, max_lateness=1000,
            seed=7,
            session_config={"count": 6, "minGapMs": 1500,
                            "maxGapMs": 4000})

    a = mk()
    ra = [jax.device_get(r) for r in a.run(4)]
    a.sync()
    b = mk()
    rb = b.run_streamed(4)
    _leaves_equal(ra, rb)


def test_count_pipeline_streamed_bit_matches():
    import jax

    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    def mk():
        return CountStreamPipeline(
            [TumblingWindow(WindowMeasure.Count, 7)], [SumAggregation()],
            throughput=2000, wm_period_ms=100, max_lateness=100, seed=0,
            out_of_order_pct=0.2)

    a = mk()
    ra = [jax.device_get(r) for r in a.run(3)]
    a.sync()
    b = mk()
    rb = b.run_streamed(3)
    _leaves_equal(ra, rb)


def test_mesh_pipeline_streamed_bit_matches():
    import jax

    from scotty_tpu.mesh import MeshKeyedPipeline

    def mk():
        return MeshKeyedPipeline(
            [TumblingWindow(Time, 100)], [SumAggregation()],
            n_keys=16, n_shards=8,
            config=EngineConfig(capacity=1 << 10, batch_size=32,
                                annex_capacity=32, min_trigger_pad=32),
            throughput=16 * 40, wm_period_ms=200, max_lateness=200,
            seed=5, gc_every=10 ** 9, value_scale=4.0)

    a = mk()
    ra = [jax.device_get(r) for r in a.run(3)]
    a.sync()
    b = mk()
    rb = b.run_streamed(3)
    _leaves_equal(ra, rb)


def test_microbatch_clean_under_transfer_guard():
    """The micro dispatch loop's only host->device movements are the
    sanctioned explicit device_puts (interval key, interval scalar,
    micro index); the streamed fetch is an explicit device_get."""
    import jax

    p = _aligned()
    p.reset()                      # state init outside the guard
    with jax.transfer_guard("disallow"):
        out = p.run_streamed(3)
    assert len(out) == 3
    p.check_overflow()


def test_microbatch_checkpoint_resume_bit_identical():
    """Snapshot the micro-batched carry BETWEEN micro-batches, restore
    into a twin, finish the interval on both — bit-identical results
    and identical continued streams."""
    import jax

    a = _aligned()
    b = _aligned()
    a.run_streamed(2)
    b.run_streamed(2)
    i = a._interval
    a.micro_start(i)
    a.micro_push()
    a.micro_push()
    snap = a.micro_snapshot()
    # poison the twin's cursors to prove restore rebuilds them
    b.micro_start(i)
    b.micro_restore(snap)
    while a._micro_m < a._micro_batch:
        a.micro_push()
    while b._micro_m < b._micro_batch:
        b.micro_push()
    fa = jax.device_get(a.micro_finish())
    fb = jax.device_get(b.micro_finish())
    _leaves_equal(fa, fb)
    a._interval += 1
    b._interval += 1
    # the continued stream stays aligned too
    _leaves_equal(a.run_streamed(2), b.run_streamed(2))


def test_microbatch_flushes_counter_and_conservation():
    """Every streamed interval is one flush (counted), every chain's
    stage sums telescope EXACTLY to its end-to-end on a ManualClock."""
    from scotty_tpu.obs.latency import LatencyTracer
    from scotty_tpu.resilience.clock import ManualClock

    clock = ManualClock()
    o = obs_mod.Observability()
    tracer = o.attach_latency(
        LatencyTracer(clock=clock, sample_every=1, exact_limit=1 << 30))
    chains = []
    _fin = tracer._finalize

    def spy(chain):
        out = _fin(chain)
        chains.append(out)
        return out

    tracer._finalize = spy
    p = _aligned()
    p.reset()
    p.set_observability(o)
    n = 4
    p.run_streamed(n)
    tracer._finalize = _fin
    snap = o.snapshot()
    assert snap.get("microbatch_flushes") == n
    assert len(chains) == n
    for c in chains:
        gap = abs(sum(c["stages"].values()) - c["end_to_end_ms"])
        assert gap == 0.0, c
        assert c["first_emit_ms"] is not None


def test_microbatch_rejects_legacy_and_serving():
    from scotty_tpu.engine.pipeline import (
        AlignedStreamPipeline,
        SlotGeometry,
    )

    with pytest.raises(NotImplementedError):
        AlignedStreamPipeline(
            [TumblingWindow(Time, 100)], [SumAggregation()],
            config=EngineConfig(capacity=1 << 10, annex_capacity=8,
                                min_trigger_pad=32, micro_batch=4),
            throughput=2000, wm_period_ms=200, max_lateness=200,
            legacy_generator=True)
    with pytest.raises(NotImplementedError):
        AlignedStreamPipeline(
            [], [SumAggregation()],
            config=EngineConfig(capacity=1 << 10, annex_capacity=8,
                                min_trigger_pad=32, micro_batch=4),
            throughput=2000, wm_period_ms=200, max_lateness=200,
            query_slots=SlotGeometry(n_slots=8, triggers_per_slot=4,
                                     slice_grid=100, max_size=400))
