"""Sliding-window operator tests — transliterated from
slicing/src/test/.../windowTest/SlidingWindowOperatorTest.java."""

import pytest

from scotty_tpu import (
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)

from conftest import make_operator


@pytest.fixture(params=["host", "engine"])
def op(request):
    return make_operator(request.param)


def sum_fn():
    # same host semantics as ReduceAggregateFunction(a+b), plus a device
    # realization — the goldens drive both operators (conftest.make_operator)
    return SumAggregation()


def test_in_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[2].get_agg_values()[0] == 1
    assert not results[1].has_value()
    assert results[0].get_agg_values()[0] == 2

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 5  # 45 - 55
    assert results[1].get_agg_values()[0] == 5  # 40 - 50
    assert results[2].get_agg_values()[0] == 4  # 35 - 45
    assert results[3].get_agg_values()[0] == 4  # 30 - 40
    assert results[4].get_agg_values()[0] == 3  # 25 - 35
    assert results[5].get_agg_values()[0] == 3  # 20 - 30
    assert results[6].get_agg_values()[0] == 2  # 15 - 25


def test_in_order_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))
    op.process_element(1, 0)
    op.process_element(2, 0)
    op.process_element(3, 20)
    op.process_element(4, 30)
    op.process_element(5, 40)

    results = op.process_watermark(22)
    assert not results[0].has_value()              # 10 - 20
    assert not results[1].has_value()              # 5 - 15
    assert results[2].get_agg_values()[0] == 3     # 0 - 10

    results = op.process_watermark(55)
    assert not results[0].has_value()              # 45 - 55
    assert results[1].get_agg_values()[0] == 5     # 40 - 50
    assert results[2].get_agg_values()[0] == 5     # 35 - 45
    assert results[3].get_agg_values()[0] == 4     # 30 - 40
    assert results[4].get_agg_values()[0] == 4     # 25 - 35
    assert results[5].get_agg_values()[0] == 3     # 20 - 30
    assert results[6].get_agg_values()[0] == 3     # 15 - 25


def test_in_order_two_windows(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))
    op.process_element(1, 1)
    op.process_element(2, 19)
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 2     # 10 - 20
    assert not results[1].has_value()              # 5 - 15
    assert results[2].get_agg_values()[0] == 1     # 0 - 10
    assert results[3].get_agg_values()[0] == 3     # 0 - 20

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 5     # 45 - 55
    assert results[1].get_agg_values()[0] == 5     # 40 - 50
    assert results[2].get_agg_values()[0] == 4     # 35 - 45
    assert results[3].get_agg_values()[0] == 4     # 30 - 40
    assert results[4].get_agg_values()[0] == 3     # 25 - 35
    assert results[5].get_agg_values()[0] == 3     # 20 - 30
    assert results[6].get_agg_values()[0] == 2     # 15 - 25
    assert results[7].get_agg_values()[0] == 7     # 20 - 40


def test_in_order_two_windows_dynamic(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))

    op.process_element(1, 1)
    op.process_element(2, 19)
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))
    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 2
    assert not results[1].has_value()
    assert results[2].get_agg_values()[0] == 1
    assert results[3].get_agg_values()[0] == 3

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 5
    assert results[1].get_agg_values()[0] == 5
    assert results[2].get_agg_values()[0] == 4
    assert results[3].get_agg_values()[0] == 4
    assert results[4].get_agg_values()[0] == 3
    assert results[5].get_agg_values()[0] == 3
    assert results[6].get_agg_values()[0] == 2
    assert results[7].get_agg_values()[0] == 7


def test_in_order_two_windows_dynamic_2(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(TumblingWindow(WindowMeasure.Time, 20))

    op.process_element(1, 1)
    op.process_element(2, 19)

    results = op.process_watermark(22)
    assert results[0].get_agg_values()[0] == 3

    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))

    op.process_element(3, 29)
    op.process_element(4, 39)
    op.process_element(5, 49)

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 7
    assert results[1].get_agg_values()[0] == 5
    assert results[2].get_agg_values()[0] == 5
    assert results[3].get_agg_values()[0] == 4
    assert results[4].get_agg_values()[0] == 4
    assert results[5].get_agg_values()[0] == 3
    assert results[6].get_agg_values()[0] == 3


def test_out_of_order(op):
    op.add_window_function(sum_fn())
    op.add_window_assigner(SlidingWindow(WindowMeasure.Time, 10, 5))
    op.process_element(1, 1)

    op.process_element(1, 30)
    op.process_element(1, 20)
    op.process_element(1, 23)
    op.process_element(1, 25)

    op.process_element(1, 45)

    results = op.process_watermark(22)
    assert not results[0].has_value()              # 10 - 20
    assert not results[1].has_value()              # 5 - 15
    assert results[2].get_agg_values()[0] == 1     # 0 - 10

    results = op.process_watermark(55)
    assert results[0].get_agg_values()[0] == 1     # 45 - 55
    assert results[1].get_agg_values()[0] == 1     # 40 - 50
    assert not results[2].has_value()              # 35 - 45
    assert results[3].get_agg_values()[0] == 1     # 30 - 40
    assert results[4].get_agg_values()[0] == 2     # 25 - 35
    assert results[5].get_agg_values()[0] == 3     # 20 - 30
    assert results[6].get_agg_values()[0] == 2     # 15 - 25
