"""Bucket-baseline correctness + config-driven runner end-to-end (CPU)."""

import json
import os

import numpy as np
import pytest

from scotty_tpu import (
    DDSketchQuantileAggregation,
    MaxAggregation,
    SlidingWindow,
    SumAggregation,
    TumblingWindow,
    WindowMeasure,
)
from scotty_tpu.bench.buckets import BucketWindowPipeline
from scotty_tpu.engine import EngineConfig
from scotty_tpu.engine.pipeline import AlignedStreamPipeline

Time = WindowMeasure.Time
CFG = EngineConfig(capacity=1 << 12, annex_capacity=8, min_trigger_pad=32)


def test_buckets_match_aligned():
    """Same generator stream; no sharing vs slicing must agree per window."""
    windows = [SlidingWindow(Time, 60, 20), TumblingWindow(Time, 50)]
    mk = lambda: [SumAggregation(), MaxAggregation()]  # noqa: E731
    a = AlignedStreamPipeline(windows, mk(), config=CFG, throughput=3000,
                              wm_period_ms=100, gc_every=10 ** 9)
    b = BucketWindowPipeline(windows, mk(), throughput=3000,
                             wm_period_ms=100, chunk=1 << 10)
    a.reset()
    b.reset()
    for i in range(6):
        ra = a.lowered_results(a.run(1)[0])
        rb = b.lowered_results(b.run(1)[0])
        assert [(s, e, c) for s, e, c, _ in ra] == \
            [(s, e, c) for s, e, c, _ in rb], (i, ra, rb)
        for (_, _, _, va), (_, _, _, vb) in zip(ra, rb):
            for x, y in zip(va, vb):
                assert float(x) == pytest.approx(float(y), rel=1e-4)


def test_buckets_prefill_equals_run():
    windows = [TumblingWindow(Time, 40)]
    b1 = BucketWindowPipeline(windows, [SumAggregation()], throughput=2000,
                              wm_period_ms=40, chunk=1 << 10)
    b2 = BucketWindowPipeline(windows, [SumAggregation()], throughput=2000,
                              wm_period_ms=40, chunk=1 << 10)
    b1.reset()
    b2.reset()
    b1.prefill(4)
    b2.run(4, collect=False)
    r1 = b1.lowered_results(b1.run(1)[0])
    r2 = b2.lowered_results(b2.run(1)[0])
    assert r1 == r2


def test_aligned_sketch_quantile():
    """Sparse (one-hot densified) sketch lift on the aligned pipeline:
    uniform values → median ≈ scale/2 within DDSketch relative accuracy."""
    p = AlignedStreamPipeline(
        [TumblingWindow(Time, 50)], [DDSketchQuantileAggregation(0.5)],
        config=CFG, throughput=20_000, wm_period_ms=100, gc_every=10 ** 9)
    p.reset()
    rows = []
    for i in range(3):
        rows += p.lowered_results(p.run(1)[0])
    assert rows, "no windows emitted"
    for (_s, _e, c, vals) in rows:
        assert c == 1000                      # 50 ms × 20 tuples/ms
        assert vals[0] == pytest.approx(5000, rel=0.25)


def test_runner_end_to_end(tmp_path):
    """python -m scotty_tpu.bench on a tiny config: every cell completes,
    emits windows, and writes result_<name>.json."""
    cfg_path = tmp_path / "tiny.json"
    cfg_path.write_text(json.dumps({
        "name": "tiny",
        "throughput": 30_000,
        "bucketsThroughput": 10_000,
        "runtime": 3,
        "windowConfigurations": ["Sliding(60,20)", "Tumbling(50)"],
        "configurations": ["TpuEngine", "Buckets"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 100,
        "capacity": 4096,
    }))
    from scotty_tpu.bench import load_config, run_config

    cfg = load_config(str(cfg_path))
    rows = run_config(cfg, out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert len(rows) == 4                     # 2 windows × 2 engines × 1 agg
    for row in rows:
        assert row["tuples_per_sec"] > 0
        assert row["windows_emitted"] > 0, row
        assert row["p99_emit_ms"] > 0
    out = tmp_path / "out" / "result_tiny.json"
    assert out.exists()
    assert len(json.loads(out.read_text())) == 4


def test_runner_serve_and_flight_flags(tmp_path, capsys):
    """--serve-port/--flight-capacity (ISSUE 4 satellite): the runner
    starts the live endpoint for the run, attaches a flight recorder to
    every cell's Observability, and the run completes with the endpoint
    announced and the server torn down."""
    cfg_path = tmp_path / "flight.json"
    cfg_path.write_text(json.dumps({
        "name": "flight",
        "throughput": 30_000,
        "runtime": 2,
        "windowConfigurations": ["Tumbling(50)"],
        "configurations": ["TpuEngine"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 100,
        "capacity": 4096,
    }))
    from scotty_tpu.bench.runner import main as runner_main

    rc = runner_main([str(cfg_path), "--out-dir", str(tmp_path / "out"),
                      "--serve-port", "0", "--flight-capacity", "2"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "live obs endpoint: http://127.0.0.1:" in out
    rows = json.loads((tmp_path / "out" / "result_flight.json").read_text())
    assert len(rows) == 1 and "error" not in rows[0]
    # the flight recorder rode the cell: a 2-slot ring wraps on the very
    # first drain sample, and the wraparound count is REPORTED in the
    # cell's embedded metrics (the obs diff gate sees it) — never silent
    assert rows[0]["metrics"]["metrics"]["flight_dropped_events"] > 0


def test_runner_ooo_fallback(tmp_path):
    """outOfOrderPct > 0 routes to the batch-at-a-time annex path."""
    cfg_path = tmp_path / "ooo.json"
    cfg_path.write_text(json.dumps({
        "name": "ooo",
        "throughput": 20_000,
        "runtime": 2,
        "windowConfigurations": ["Tumbling(200)"],
        "configurations": ["TpuEngine"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 500,
        "batchSize": 4096,
        "capacity": 4096,
        "outOfOrderPct": 0.05,
        "maxLateness": 1000,
    }))
    from scotty_tpu.bench import load_config, run_config

    cfg = load_config(str(cfg_path))
    rows = run_config(cfg, out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert rows[0]["windows_emitted"] > 0


def test_micro_suite_small():
    """Per-phase microbenchmarks run and report every phase (VERDICT r1
    item 9 — SlicingWindowOperatorBenchmark.java:37-52 analogue)."""
    from scotty_tpu.bench.micro import run_micro

    res = run_micro(small=True, iters=1)
    for phase in ("ingest_scatter", "ingest_aligned", "query",
                  "annex_merge", "gc", "host_pack"):
        assert phase in res, phase
        assert res[phase]["mean_ms"] > 0
    assert res["ingest_scatter"]["tuples_per_s"] > 0
    assert res["query"]["windows_per_s"] > 0


def test_band_spec_runs_through_fused_stream_pipeline():
    """FixedBand specs can't use the slice-aligned pipeline; they must still
    run fused (one dispatch per interval via StreamPipeline), not
    batch-at-a-time (VERDICT r1: StreamPipeline was dead code)."""
    from scotty_tpu.bench.harness import BenchmarkConfig
    from scotty_tpu.bench.runner import run_cell

    cfg = BenchmarkConfig(name="band", throughput=100_000, runtime_s=3,
                          batch_size=1 << 12, capacity=1 << 12,
                          watermark_period_ms=1000)
    res = run_cell(cfg, "FixedBand(500,1000)+Tumbling(1000)", "sum",
                   "TpuEngine")
    assert res.n_windows_emitted > 0
    assert res.tuples_per_sec > 0


def test_charts_render_from_results(tmp_path):
    """Chart generation consumes the runner's JSON schema and writes both
    figures (charts/*.png parity with the reference README figures)."""
    import json

    matplotlib = pytest.importorskip("matplotlib")  # noqa: F841
    from scotty_tpu.bench.charts import main as charts_main

    res = tmp_path / "results"
    res.mkdir()
    sliding = []
    for sl in (60000, 10000, 1000, 500, 250, 100, 1):
        for eng, tps in (("TpuEngine", 4e9), ("Buckets", 5e5)):
            sliding.append({"windows": f"Sliding(60000,{sl})",
                            "engine": eng, "tuples_per_sec": tps})
    (res / "result_sliding-suite.json").write_text(json.dumps(sliding))
    tumbling = []
    for n in (1, 10, 100, 1000):
        for eng, tps in (("TpuEngine", 4e9), ("Buckets", 2e6)):
            tumbling.append({"windows": f"randomTumbling({n},1000,20000)",
                             "engine": eng, "tuples_per_sec": tps})
    (res / "result_random-tumbling.json").write_text(json.dumps(tumbling))

    out = tmp_path / "charts"
    charts_main(results_dir=str(res), out_dir=str(out))
    assert (out / "sliding_suite.png").stat().st_size > 10_000
    assert (out / "concurrent_tumbling.png").stat().st_size > 10_000


def test_runner_count_measure_cells(tmp_path):
    """Count-measure cells (VERDICT r3 item 6): the randomCount DSL routes
    through the record-buffer path, in-order AND out-of-order, including
    the r4 count+time OOO mix — small shapes of
    bench/configurations/count_measure*.json."""
    import json as _json

    from scotty_tpu.bench import load_config, run_config

    for ooo in (0.0, 0.05):
        cfg_path = tmp_path / f"count{int(ooo*100)}.json"
        cfg_path.write_text(_json.dumps({
            "name": f"count{int(ooo*100)}",
            "throughput": 20_000,
            "runtime": 3,
            "windowConfigurations": ["CountTumbling(70)",
                                     "CountTumbling(70)+Tumbling(500)"],
            "configurations": ["TpuEngine"],
            "aggFunctions": ["sum"],
            "watermarkPeriodMs": 500,
            "batchSize": 4096,
            "capacity": 8192,
            "recordCapacity": 1 << 17,
            "outOfOrderPct": ooo,
            "maxLateness": 1000,
        }))
        cfg = load_config(str(cfg_path))
        rows = run_config(cfg, out_dir=str(tmp_path / "out"),
                          echo=lambda *a, **k: None)
        for row in rows:
            assert "error" not in row, row
            assert row["windows_emitted"] > 0, (ooo, row)
            assert row["tuples_per_sec"] > 0


def test_runner_context_chaos_cells(tmp_path):
    """ISSUE 11: the ContextChaos engine runs all three window classes
    (speculative generic, tuned session, scan-bound capped) at tiny
    shapes with the three-way oracle arm green and the speculative
    telemetry serialized."""
    import json as _json

    from scotty_tpu.bench import load_config, run_config

    cfg_path = tmp_path / "ctx.json"
    cfg_path.write_text(_json.dumps({
        "name": "ctx",
        "throughput": 30_000,
        "runtime": 8,
        "windowConfigurations": ["GenericSession(120)",
                                 "CappedSession(150,400)"],
        "configurations": ["ContextChaos"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 1000,
        "batchSize": 65536,
        "capacity": 1024,
        "outOfOrderPct": 0.2,
        "maxLateness": 1000,
    }))
    rows = run_config(load_config(str(cfg_path)),
                      out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert len(rows) == 2
    for row in rows:
        assert "error" not in row, row
        assert row["oracle_match"] and row["scan_match"], row
        assert row["windows_emitted"] > 0 and row["oracle_windows"] > 0
        assert "ctx_fallback_rate" in row
    assert rows[0]["context_mode"] == "speculative"
    assert rows[1]["context_mode"] == "scan"


def test_runner_count_fused_and_ring_fed_cells(tmp_path):
    """ISSUE 11: the CountFused (sliding count + oracle arm) and RingFed
    (external headline + in-program/legacy comparators + generator
    share) engines run end-to-end at tiny shapes."""
    import json as _json

    from scotty_tpu.bench import load_config, run_config

    cfg_path = tmp_path / "sc.json"
    cfg_path.write_text(_json.dumps({
        "name": "sc",
        "throughput": 20_000,
        "runtime": 4,
        "windowConfigurations": ["CountSliding(700,200)"],
        "configurations": ["CountFused"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 500,
        "batchSize": 4096,
        "capacity": 8192,
        "outOfOrderPct": 0.1,
        "maxLateness": 300,
    }))
    rows = run_config(load_config(str(cfg_path)),
                      out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert len(rows) == 1 and "error" not in rows[0], rows
    assert rows[0]["oracle_match"] and rows[0]["windows_emitted"] > 0
    assert rows[0]["tuples_per_sec_inorder"] > 0

    cfg_path = tmp_path / "rf.json"
    cfg_path.write_text(_json.dumps({
        "name": "rf",
        "throughput": 200_000,
        "runtime": 4,
        "windowConfigurations": ["Sliding(4000,1000)"],
        "configurations": ["RingFed"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 1000,
        "batchSize": 32768,
        "capacity": 8192,
        "maxLateness": 1000,
    }))
    rows = run_config(load_config(str(cfg_path)),
                      out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert len(rows) == 1 and "error" not in rows[0], rows
    row = rows[0]
    assert row["windows_emitted"] > 0
    assert row["inprogram_tps"] > 0 and 0.0 < row["generator_share"] <= 1.0
    assert row["legacy_anchor_tps"] > 0


def test_runner_latency_headline_cell(tmp_path, monkeypatch):
    """ISSUE 14: the LatencyHeadline engine runs end-to-end at a tiny
    shape — full stage decomposition with exact conservation, measured
    first-emit dimension, oracle arm green, and the cell JSON embeds
    the standing latency fields. The interleaved overhead arm is
    monkeypatched (it compiles two extra aligned pipelines — measured
    for real by the recorded artifact, not per CI run)."""
    import json as _json

    from scotty_tpu.bench import load_config, run_config
    from scotty_tpu.bench import runner as _runner

    monkeypatch.setattr(_runner, "measure_latency_overhead",
                        lambda **kw: 0.0)
    cfg_path = tmp_path / "lh.json"
    cfg_path.write_text(_json.dumps({
        "name": "lh",
        "throughput": 100_000,
        "runtime": 4,
        "windowConfigurations": ["Sliding(4000,1000)"],
        "configurations": ["LatencyHeadline"],
        "aggFunctions": ["sum"],
        "watermarkPeriodMs": 1000,
        "batchSize": 16384,
        "capacity": 8192,
        "maxLateness": 1000,
    }))
    rows = run_config(load_config(str(cfg_path)),
                      out_dir=str(tmp_path / "out"),
                      echo=lambda *a, **k: None)
    assert len(rows) == 1 and "error" not in rows[0], rows
    row = rows[0]
    assert row["oracle_match"] and row["oracle_windows"] > 0
    assert row["latency_conservation_ok"]
    assert row["latency_chains"] > 0
    assert row["first_emit_samples"] > 0
    assert row["first_emit_p99_ms"] >= row["first_emit_p50_ms"] > 0
    stages = row["latency_stages_ms"]
    # the full edge decomposes: ring + dispatch + delivery stages
    for s in ("ring_enqueue", "ring_dequeue", "eligibility", "drain",
              "emit", "sink"):
        assert s in stages, (s, sorted(stages))
    # written cell JSON carries the dimension (the standing-field check)
    disk = _json.load(open(tmp_path / "out" / "result_lh.json"))
    assert disk[0]["first_emit_p99_ms"] == row["first_emit_p99_ms"]
    # and `obs latency` attributes the written artifact, exit 0
    from scotty_tpu.obs.report import main as obs_main

    assert obs_main(["latency",
                     str(tmp_path / "out" / "result_lh.json")]) == 0


def test_latency_stats_stall_robust():
    """VERDICT r4 weak #5: a tunnel stall in the sample set must not be
    the only published percentile — trimmed companion + stall count."""
    import numpy as np

    from scotty_tpu.bench.harness import latency_stats

    lats = [50.0] * 49 + [26720.0]          # one documented transport stall
    s = latency_stats(lats)
    assert s["stall_flagged"]
    assert s["n_stall_samples"] == 1
    assert s["p99_emit_ms_trimmed"] <= 51.0
    assert s["p99_emit_ms"] > 10000          # raw stays honest

    healthy = latency_stats(np.linspace(40, 60, 100))
    assert not healthy["stall_flagged"]
    assert healthy["n_stall_samples"] == 0


def test_assume_inorder_deprecated():
    import pytest

    from scotty_tpu.hybrid import HybridWindowOperator

    with pytest.warns(DeprecationWarning, match="assume_inorder"):
        HybridWindowOperator(assume_inorder=True)
