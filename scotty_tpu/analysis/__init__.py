"""Invariant linter: the codebase's hard-won rules as enforced checks.

Every review pass before this package existed re-caught the same
statically-detectable bug classes by hand: numpy-backed leaves fed to
donating kernels (the ISSUE 2 checkpoint-restore segfault), committed
bytes written around :mod:`scotty_tpu.utils.fsio` (ISSUE 8 found three
such paths by hand), string-literal flight-event kinds (the ISSUE 6
review finding), host syncs creeping into jitted paths, and silent
``except``-swallows in the ingest/delivery layers — and every PR
re-verified "aligned-step HLO hash byte-identical" manually.  This
package turns those review rituals into tooling, the way LLVM-class
projects gate merges on clang-tidy-style custom checks:

* :mod:`.core` — the framework: one AST parse per file, a rule
  registry, per-rule inline suppressions
  (``# scotty: allow(<rule>) — <reason>``; a reasonless suppression is
  itself a finding), and a baseline file for grandfathered findings.
* :mod:`.rules` — the rule set encoding the invariants the repo
  already bleeds for (``python -m scotty_tpu.analysis check --list``
  prints the catalog; docs/API.md "Static analysis" maps each rule to
  the incident that motivated it).
* :mod:`.hlo` — the canonical aligned/session/count step lowerings and
  their sha256 pins (``pin-hlo``), ending the manual per-PR
  "verified byte-identical" ritual: accidental jitted-path drift is a
  red test, deliberate drift is one ``pin-hlo --update`` with the diff
  in review.
* :mod:`.cli` — ``python -m scotty_tpu.analysis check [--rule ...]
  [--format text|json] [--write-baseline]``; nonzero exit on new
  findings, so it runs unchanged in CI and inside tier-1
  (tests/test_analysis.py).
"""

from .core import (  # noqa: F401
    Finding,
    Project,
    Rule,
    RULES,
    default_root,
    load_baseline,
    run_check,
    write_baseline,
)
from . import rules as _rules  # noqa: F401, E402  (populates RULES)

__all__ = [
    "Finding", "Project", "Rule", "RULES", "default_root",
    "load_baseline", "run_check", "write_baseline",
]
