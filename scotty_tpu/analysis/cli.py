"""``python -m scotty_tpu.analysis`` — the invariant linter CLI.

Subcommands::

    check   [--rule R]... [--format text|json] [--baseline FILE]
            [--write-baseline] [--root DIR] [--list]
        Run the rule set over scotty_tpu/ + tests/ + bench.py.
        Exit 0: no new findings (suppressed/baselined are reported but
        don't fail). Exit 1: new findings. ``--write-baseline``
        grandfathers the current findings into the baseline file and
        exits 0 — reviewed like any other committed file.

    pin-hlo [--update] [--pins FILE] [--step NAME]...
        Verify the canonical aligned/session/count step lowerings
        against tests/hlo_pins.json (exit 1 on drift); ``--update``
        refreshes the pins — the hash diff rides the commit.

All output flows through an overridable echo sink (the package's own
no-print rule covers this module too).
"""

from __future__ import annotations

import argparse
import json
from typing import Optional

from ..utils import stdout_echo
from . import rules as _rules  # noqa: F401  (populates the registry)
from .core import (
    Project, RULES, SUPPRESSION_FORMAT, default_root, load_baseline,
    run_check, write_baseline,
)

#: default baseline location, repo-root-relative (committed; empty on a
#: clean tree — the mechanism exists for grandfathering future rules)
BASELINE_PATH = "analysis_baseline.json"


def check_main(rule_names=None, fmt: str = "text", root=None,
               baseline_path=None, write_baseline_flag: bool = False,
               list_rules: bool = False, echo=None) -> int:
    if echo is None:
        echo = stdout_echo
    if list_rules:
        for name in sorted(RULES):
            echo(f"{name}: {RULES[name].doc}")
        return 0
    root = root or default_root()
    if rule_names:
        unknown = [r for r in rule_names if r not in RULES]
        if unknown:
            echo(f"unknown rule(s): {', '.join(unknown)} "
                 f"(known: {', '.join(sorted(RULES))})")
            return 2
        selected = [RULES[r] for r in rule_names]
    else:
        selected = list(RULES.values())
    bl_path = baseline_path or (root / BASELINE_PATH)
    baseline = load_baseline(bl_path)
    project = Project(root)
    new, suppressed, baselined = run_check(
        project, selected, baseline=baseline)
    if write_baseline_flag:
        # a partial run (--rule X) must not drop OTHER rules' existing
        # entries — including suppression-format ones, which a partial
        # run can only re-derive for the SELECTED rules' allows. Only a
        # full run regenerates them all, so only a full run may rewrite
        # them (stale entries left by a partial run are inert).
        checked = {r.name for r in selected}
        if checked == set(RULES):
            checked.add(SUPPRESSION_FORMAT)
        keep = [k for k in baseline if k[0] not in checked]
        write_baseline(bl_path, new + baselined, keep_keys=keep)
        echo(f"baseline written: {bl_path} ({len(new)} new + "
             f"{len(baselined)} existing grandfathered)")
        return 0
    if fmt == "json":
        echo(json.dumps({
            "new": [f.to_dict() for f in new],
            "suppressed": len(suppressed),
            "baselined": len(baselined),
        }, indent=1))
    else:
        for f in new:
            echo(f.render())
        echo(f"{len(new)} new finding(s), {len(suppressed)} suppressed, "
             f"{len(baselined)} baselined "
             f"({len(project.sources)} files, "
             f"{len(selected)} rule(s))")
    return 1 if new else 0


def pin_hlo_main(update: bool = False, pins_file=None, steps=None,
                 echo=None) -> int:
    if echo is None:
        echo = stdout_echo
    # the mesh step lowers over an 8-device mesh, and the flag must land
    # before anything initializes a JAX backend — the CLI owns its
    # process, so set it here (tier-1's conftest does the same; the
    # single-device steps' lowerings are device-count-independent, which
    # tests/test_hlo_pinning.py pins either way)
    import os

    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    from . import hlo

    names = list(steps or hlo.CANONICAL_STEPS)
    unknown = [n for n in names if n not in hlo.CANONICAL_STEPS]
    if unknown:
        echo(f"unknown step(s): {', '.join(unknown)} "
             f"(known: {', '.join(hlo.CANONICAL_STEPS)})")
        return 2
    # pins load BEFORE the (slow) lowerings: a missing file in verify
    # mode and a CORRUPT file in either mode fail fast — silently
    # resetting a corrupt file would discard the other steps' lineage
    # hashes on a --step subset update, so ValueError propagates
    path = pins_file or hlo.pins_path()
    try:
        pins = hlo.load_pins(path)
    except OSError:
        if not update:
            echo(f"no pins file at {path} — run pin-hlo --update first")
            return 2
        pins = {}           # no pins yet: a fresh file is the point
    current = {n: hlo.step_hash(n) for n in names}
    if update:
        pins.update(current)
        hlo.write_pins(pins, path)
        for n in names:
            echo(f"{n}: {current[n]}")
        echo(f"pins written: {path}")
        return 0
    drift = 0
    for n in names:
        want = pins.get(n)
        status = "OK" if current[n] == want else "DRIFT"
        if current[n] != want:
            drift += 1
        echo(f"{n}: {status} {current[n]}"
             + ("" if current[n] == want else f" (pinned {want})"))
    if drift:
        echo(f"{drift} step lowering(s) drifted — deliberate? "
             "pin-hlo --update and let review see the hash diff")
    return 1 if drift else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m scotty_tpu.analysis",
        description="invariant linter + HLO pinning "
                    "(scotty_tpu.analysis)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    cp = sub.add_parser(
        "check", help="run the rule set; nonzero exit on new findings")
    cp.add_argument("--rule", action="append", metavar="NAME",
                    help="run only this rule (repeatable)")
    cp.add_argument("--format", choices=("text", "json"), default="text")
    cp.add_argument("--root", default=None,
                    help="project root (default: the repo holding "
                         "scotty_tpu)")
    cp.add_argument("--baseline", default=None, metavar="FILE",
                    help=f"baseline file (default <root>/{BASELINE_PATH})")
    cp.add_argument("--write-baseline", action="store_true",
                    help="grandfather the current findings and exit 0")
    cp.add_argument("--list", action="store_true",
                    help="print the rule catalog and exit")
    hp = sub.add_parser(
        "pin-hlo", help="verify (or --update) the canonical step "
                        "lowerings against tests/hlo_pins.json")
    hp.add_argument("--update", action="store_true")
    hp.add_argument("--pins", default=None, metavar="FILE")
    hp.add_argument("--step", action="append", metavar="NAME",
                    help="pin only this step config (repeatable)")
    args = ap.parse_args(argv)
    if args.cmd == "check":
        import pathlib

        return check_main(
            rule_names=args.rule, fmt=args.format,
            root=pathlib.Path(args.root) if args.root else None,
            baseline_path=args.baseline,
            write_baseline_flag=args.write_baseline,
            list_rules=args.list)
    if args.cmd == "pin-hlo":
        return pin_hlo_main(update=args.update, pins_file=args.pins,
                            steps=args.step)
    return 2


if __name__ == "__main__":      # pragma: no cover
    raise SystemExit(main())
