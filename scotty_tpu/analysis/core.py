"""Framework for the invariant linter: parsed sources, rule registry,
inline suppressions, and the grandfathering baseline.

Design constraints that shaped this module:

* **One parse per file.** Rules never call ``ast.parse`` themselves —
  a :class:`SourceFile` carries the tree, the raw lines, and a
  prebuilt flat node list (``walk``) shared by every rule, so the
  whole-tree check stays O(files), not O(files × rules).
* **Suppressions carry reasons.** ``# scotty: allow(<rule>) —
  <reason>`` on the offending line (or the line directly above)
  silences that rule there; an allow comment with no reason is
  reported as a :data:`SUPPRESSION_FORMAT` finding — the acceptance
  bar is "zero findings left unexplained", so the explanation is part
  of the syntax.
* **Baselines grandfather, never bless.** A baseline entry matches on
  ``(rule, path, snippet)`` — the stripped source line, not the line
  number — so unrelated edits above a grandfathered finding don't
  resurrect it, while touching the offending line itself does.
"""

from __future__ import annotations

import ast
import json
import pathlib
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

#: pseudo-rule emitted by the framework itself for malformed/reasonless
#: suppression comments (cannot be suppressed)
SUPPRESSION_FORMAT = "suppression-format"

#: ``# scotty: allow(rule-a, rule-b) — reason`` (also accepts ``--`` and
#: ``:`` as the reason separator so plain-ASCII editors work)
_ALLOW_RE = re.compile(
    r"#\s*scotty:\s*allow\(([^)]*)\)\s*(?:—|--|:)?\s*(.*)$")


@dataclass(frozen=True)
class Finding:
    """One rule violation at a source location."""

    rule: str
    path: str          # project-root-relative, '/'-separated
    line: int          # 1-based
    message: str
    snippet: str = ""  # stripped source line (baseline fingerprint)

    def key(self) -> Tuple[str, str, str]:
        """Baseline identity: stable across pure line-number drift."""
        return (self.rule, self.path, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "message": self.message, "snippet": self.snippet}


@dataclass
class SourceFile:
    """One parsed Python source: path, text, lines, AST, flat node list."""

    rel: str                       # project-root-relative path
    text: str
    tree: ast.AST
    lines: List[str] = field(default_factory=list)
    walk: List[ast.AST] = field(default_factory=list)
    _allows: Optional[Dict] = field(default=None, repr=False)

    @classmethod
    def parse(cls, root: pathlib.Path, rel: str) -> "SourceFile":
        text = (root / rel).read_text()
        tree = ast.parse(text, filename=rel)
        return cls(rel=rel, text=text, tree=tree,
                   lines=text.splitlines(), walk=list(ast.walk(tree)))

    def line_at(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""

    # -- suppressions ------------------------------------------------------
    def allows(self) -> Dict[int, Tuple[Tuple[str, ...], str, int]]:
        """Map of line → (rules, reason, comment_line) for every
        ``# scotty: allow(...)`` comment. A suppression covers its own
        line (trailing-comment form) and the first CODE line after it —
        continuation comment lines in between extend the reason, so a
        multi-line explanation still reaches the statement below it.
        Computed once per file (pure function of the source) — findings
        share the cached map."""
        if self._allows is not None:
            return self._allows
        out: Dict[int, Tuple[Tuple[str, ...], str, int]] = {}
        for i, raw in enumerate(self.lines, start=1):
            m = _ALLOW_RE.search(raw)
            if not m:
                continue
            rules = tuple(r.strip() for r in m.group(1).split(",")
                          if r.strip())
            reason = m.group(2).strip()
            entry = (rules, reason, i)
            out[i] = entry
            j = i + 1
            while j <= len(self.lines) \
                    and self.lines[j - 1].lstrip().startswith("#"):
                j += 1
            out.setdefault(j, entry)
        self._allows = out
        return out


class Project:
    """A set of parsed sources under one root, plus non-Python documents
    rules may want (docs/README for the coherence checks)."""

    #: directories never walked (seeded violations live in the corpus!)
    SKIP_DIRS = ("__pycache__", "analysis_corpus")

    def __init__(self, root, rel_paths: Optional[Sequence[str]] = None,
                 doc_paths: Optional[Sequence[str]] = None):
        self.root = pathlib.Path(root)
        if rel_paths is None:
            rel_paths = self.discover(self.root)
        self.sources: Dict[str, SourceFile] = {}
        self.errors: List[Finding] = []
        for rel in rel_paths:
            try:
                self.sources[rel] = SourceFile.parse(self.root, rel)
            except SyntaxError as e:
                self.errors.append(Finding(
                    rule="parse-error", path=rel, line=e.lineno or 0,
                    message=f"syntax error: {e.msg}"))
        if doc_paths is None:
            doc_paths = [p for p in ("docs/API.md", "README.md")
                         if (self.root / p).is_file()]
        self.docs: Dict[str, str] = {
            p: (self.root / p).read_text() for p in doc_paths}

    @classmethod
    def discover(cls, root: pathlib.Path) -> List[str]:
        """The WALKED tree: ``scotty_tpu/`` + ``tests/`` + the root
        ``bench.py`` shim — every file is parsed (syntax errors flag
        regardless of rule scopes), then each rule restricts itself via
        ``include``/``exclude``. The corpus of seeded violations under
        ``tests/analysis_corpus/`` is excluded by construction."""
        rels: List[str] = []
        for top in ("scotty_tpu", "tests"):
            base = root / top
            if not base.is_dir():
                continue
            for path in sorted(base.rglob("*.py")):
                rel = path.relative_to(root).as_posix()
                if any(f"/{d}/" in f"/{rel}" or rel.startswith(f"{d}/")
                       for d in cls.SKIP_DIRS):
                    continue
                rels.append(rel)
        if (root / "bench.py").is_file():
            rels.append("bench.py")
        return rels


class Rule:
    """Base class: subclass, set the class attrs, implement ``check``
    (per-file) and/or ``check_project`` (whole-project), then decorate
    with :func:`register`.

    ``include``/``exclude`` are '/'-separated path prefixes relative to
    the project root; a file is in scope when it starts with an include
    prefix and no exclude prefix. Scope extension is therefore a
    one-line config change on the rule class.
    """

    name: str = ""
    #: one-line summary for ``check --list`` and the docs catalog
    doc: str = ""
    include: Tuple[str, ...] = ("scotty_tpu",)
    exclude: Tuple[str, ...] = ()

    @staticmethod
    def _matches(rel: str, prefix: str) -> bool:
        return rel == prefix or rel.startswith(prefix.rstrip("/") + "/")

    def in_scope(self, rel: str) -> bool:
        if not any(self._matches(rel, p) for p in self.include):
            return False
        return not any(self._matches(rel, p) for p in self.exclude)

    def check(self, src: SourceFile) -> Iterable[Finding]:
        return ()

    def check_project(self, project: Project) -> Iterable[Finding]:
        return ()

    # -- helpers shared by rules ------------------------------------------
    @staticmethod
    def finding(rule_name: str, src: SourceFile, node,
                message: str) -> Finding:
        line = getattr(node, "lineno", 0)
        return Finding(rule=rule_name, path=src.rel, line=line,
                       message=message, snippet=src.line_at(line))


#: the registry: rule name → instance (import scotty_tpu.analysis.rules
#: to populate)
RULES: Dict[str, Rule] = {}


def register(cls):
    """Class decorator: instantiate + register a rule."""
    inst = cls()
    if not inst.name:
        raise ValueError(f"rule {cls.__name__} has no name")
    if inst.name in RULES:
        raise ValueError(f"duplicate rule name {inst.name!r}")
    RULES[inst.name] = inst
    return cls


def default_root() -> pathlib.Path:
    """The repo root: the directory holding the ``scotty_tpu`` package."""
    return pathlib.Path(__file__).resolve().parent.parent.parent


# ---------------------------------------------------------------------------
# Baseline
# ---------------------------------------------------------------------------

BASELINE_SCHEMA = "scotty_tpu.analysis_baseline/1"


def load_baseline(path) -> set:
    """Grandfathered finding keys; a missing file is an empty baseline."""
    p = pathlib.Path(path)
    if not p.is_file():
        return set()
    doc = json.loads(p.read_text())
    if not str(doc.get("schema", "")).startswith(
            "scotty_tpu.analysis_baseline/"):
        raise ValueError(
            f"{path}: not an analysis baseline "
            f"(schema={doc.get('schema')!r})")
    return {(f["rule"], f["path"], f["snippet"])
            for f in doc.get("findings", [])}


def write_baseline(path, findings: Sequence[Finding],
                   keep_keys: Iterable[Tuple[str, str, str]] = ()
                   ) -> None:
    """Write the baseline from ``findings`` plus ``keep_keys`` — raw
    ``(rule, path, snippet)`` entries to retain verbatim (a partial
    ``check --rule X --write-baseline`` passes the other rules'
    existing entries here so it cannot drop them)."""
    keys = {f.key() for f in findings} | set(map(tuple, keep_keys))
    doc = {
        "schema": BASELINE_SCHEMA,
        "findings": [
            {"rule": r, "path": p, "snippet": s}
            for r, p, s in sorted(keys)],
    }
    pathlib.Path(path).write_text(json.dumps(doc, indent=1) + "\n")


# ---------------------------------------------------------------------------
# The check driver
# ---------------------------------------------------------------------------


def run_check(project: Project,
              rules: Optional[Sequence[Rule]] = None,
              baseline: Optional[set] = None,
              respect_scope: bool = True,
              ) -> Tuple[List[Finding], List[Finding], List[Finding]]:
    """Run ``rules`` (default: all registered) over ``project``.

    Returns ``(new, suppressed, baselined)``: findings not explained by
    a suppression or the baseline; findings silenced by a reasoned
    inline allow; findings grandfathered by the baseline. Reasonless or
    unparseable-rule-list allow comments surface in ``new`` as
    :data:`SUPPRESSION_FORMAT` findings. ``respect_scope=False`` runs
    every rule on every file (the corpus tests use this — corpus files
    live outside the rules' production scopes).
    """
    if rules is None:
        rules = list(RULES.values())
    baseline = baseline or set()
    raw: List[Finding] = list(project.errors)
    for src in project.sources.values():
        for rule in rules:
            if respect_scope and not rule.in_scope(src.rel):
                continue
            raw.extend(rule.check(src))
    for rule in rules:
        raw.extend(rule.check_project(project))

    # pass 1: apply suppressions; reasonless allow comments generate
    # SUPPRESSION_FORMAT findings that join the pool BEFORE the baseline
    # filter (so --write-baseline grandfathers them too and its "next
    # check exits 0" contract holds)
    pool: List[Finding] = []
    suppressed: List[Finding] = []
    format_findings: Dict[Tuple[str, int], Finding] = {}
    for f in raw:
        src = project.sources.get(f.path)
        allows = src.allows() if src is not None else {}
        entry = allows.get(f.line)
        if entry is not None and f.rule in entry[0]:
            rules_listed, reason, comment_line = entry
            if reason:
                suppressed.append(f)
                continue
            format_findings.setdefault((f.path, comment_line), Finding(
                rule=SUPPRESSION_FORMAT, path=f.path, line=comment_line,
                message="suppression without a reason: write "
                        "'# scotty: allow(%s) — <why this is deliberate>'"
                        % ", ".join(rules_listed),
                snippet=src.line_at(comment_line) if src else ""))
            # fall through: the underlying finding still counts
        pool.append(f)
    pool.extend(format_findings.values())

    # pass 2: baseline filter
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in pool:
        (baselined if f.key() in baseline else new).append(f)
    new.sort(key=lambda f: (f.path, f.line, f.rule))
    return new, suppressed, baselined
