"""HLO-hash pinning: the canonical fused-step lowerings as sha256 pins.

Every PR since ISSUE 1 closed with a manual ritual: rebuild the
canonical aligned step, hash ``lowered.as_text()``, eyeball it against
the previous PR's recorded value ("aligned-step HLO hash
byte-identical, sha256 19fd4d91…"). This module makes the ritual a
red/green test: the three canonical step configs (aligned / session /
count — the fused classes whose jitted HLO is the performance
contract) lower here, tests/hlo_pins.json records their hashes, and
``python -m scotty_tpu.analysis pin-hlo`` verifies or (``--update``)
refreshes them. Accidental jitted-path drift fails tier-1
(tests/test_hlo_pinning.py); deliberate drift is one ``--update`` with
the hash diff visible in review.

The canonical configs are deliberately tiny (seconds to trace on CPU)
and FROZEN: changing a config is indistinguishable from changing the
engine, so treat these builders as part of the pin. The aligned
builder reproduces the exact construction every PR since ISSUE 8
hashed by hand, so the recorded pin carries the lineage forward
unchanged.
"""

from __future__ import annotations

import hashlib
import json
import pathlib
from typing import Dict, Optional, Sequence

from .core import default_root

#: pins file checked by tests/test_hlo_pinning.py (tier-1)
DEFAULT_PINS_PATH = "tests/hlo_pins.json"
PINS_SCHEMA = "scotty_tpu.hlo_pins/1"


def _aligned_lowered(window_ms: int = 50):
    """The lineage config: byte-identical to the hand-run hash of
    ISSUEs 1–8 (sha256 19fd4d91… recorded at ISSUE 8)."""
    import numpy as np

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [TumblingWindow(WindowMeasure.Time, window_ms)],
        [SumAggregation()],
        config=EngineConfig(capacity=1 << 12, batch_size=256,
                            annex_capacity=256, min_trigger_pad=32),
        throughput=20_000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9, value_scale=1024.0)
    p.reset()
    return p._step.lower(p.state, p.dm, p._interval_key(0), np.int64(0))


def _session_lowered():
    import numpy as np

    from scotty_tpu import SessionWindow, SumAggregation, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.session_pipeline import SessionStreamPipeline

    p = SessionStreamPipeline(
        [SessionWindow(WindowMeasure.Time, 1000)], [SumAggregation()],
        config=EngineConfig(capacity=1 << 12, annex_capacity=8,
                            min_trigger_pad=32),
        throughput=4000, wm_period_ms=1000, max_lateness=1000, seed=7,
        session_config={"count": 6, "minGapMs": 1500, "maxGapMs": 4000})
    p.reset()
    return p._step.lower(p.state, p.sess_states, p.dm,
                         p._interval_key(0), np.int64(0), np.bool_(True))


def _count_lowered():
    import numpy as np

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine.count_pipeline import CountStreamPipeline

    p = CountStreamPipeline(
        [TumblingWindow(WindowMeasure.Count, 7)], [SumAggregation()],
        throughput=2000, wm_period_ms=100, max_lateness=100, seed=0,
        out_of_order_pct=0.2)
    p.reset()
    return p._step.lower(p.state, p.dm, p._interval_key(0), np.int64(0))


def _context_lowered():
    """Canonical generic-context chunk kernel (ISSUE 11): the vectorized
    chain/speculative dispatch unit for a capped-session decider — the
    lowering every speculative chunk run and in-order context chunk
    executes. Frozen like the other canonical configs."""
    import numpy as np

    from scotty_tpu import SumAggregation
    from scotty_tpu.engine import context as ectx
    from scotty_tpu.engine import sessions as es

    import jax

    aggs = (SumAggregation().device_spec(),)
    spec = ectx.CappedSessionDecider(10, 40)
    kern = jax.jit(ectx.build_context_chunk(aggs, spec, 256, 256),
                   donate_argnums=0)
    st = es.init_session_state(aggs, 256, orphan_capacity=64)
    ts = np.arange(256, dtype=np.int64)
    vals = np.ones(256, np.float32)
    m = np.ones(256, bool)
    return kern.lower(st, ts, vals, m)


def _mesh_lowered():
    """Canonical mesh-sharded keyed step (ISSUE 10): 16 keys over the
    8-device virtual mesh — the shard_map per-shard program + the
    in-executable psum global fold. Needs 8 devices BEFORE jax
    initializes: tier-1's conftest forces them, and the ``pin-hlo`` CLI
    sets the same flag when it owns the process (a live backend with
    fewer devices fails loudly here instead of pinning a different
    topology's lowering)."""
    import jax
    import numpy as np

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.mesh import MeshKeyedPipeline

    if len(jax.devices()) < 8:
        raise RuntimeError(
            "the mesh pin lowers over an 8-device mesh; run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8 (set "
            "before anything initializes a JAX backend)")
    p = MeshKeyedPipeline(
        [TumblingWindow(WindowMeasure.Time, 50)], [SumAggregation()],
        n_keys=16, n_shards=8,
        config=EngineConfig(capacity=1 << 10, batch_size=32,
                            annex_capacity=32, min_trigger_pad=32),
        throughput=16 * 2000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9)
    p.reset()
    return p._step.lower(p.state, p._interval_key(0),
                         jax.device_put(np.int64(0)))


def _mesh_serving_lowered():
    """Canonical fused mesh-serving step (ISSUE 13): 16 keys over the
    8-device virtual mesh with an 8x4 query-slot table REPLICATED in
    the donated carry — the shard_map per-shard program, trigger rows
    read from table data, and the per-query psum global fold. Same
    8-device precondition as the mesh pin."""
    import jax
    import numpy as np

    from scotty_tpu import SumAggregation
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.pipeline import SlotGeometry
    from scotty_tpu.mesh_serving import MeshServingPipeline

    if len(jax.devices()) < 8:
        raise RuntimeError(
            "the mesh_serving pin lowers over an 8-device mesh; run "
            "under XLA_FLAGS=--xla_force_host_platform_device_count=8 "
            "(set before anything initializes a JAX backend)")
    p = MeshServingPipeline(
        [SumAggregation()],
        query_slots=SlotGeometry(n_slots=8, triggers_per_slot=4,
                                 slice_grid=50, max_size=400),
        n_keys=16, n_shards=8,
        config=EngineConfig(capacity=1 << 10, batch_size=32,
                            annex_capacity=32, min_trigger_pad=32),
        throughput=16 * 2000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9)
    p.reset()
    return p._step.lower(p.state, p._qstate, p._interval_key(0),
                         jax.device_put(np.int64(0)))


def _keyed_lowered():
    """Canonical keyed aligned step (ISSUE 10 machinery; pinned since
    ISSUE 15): 4 keys, tiny shapes — the vmapped per-key fold + append
    + range query whose flags-off lowering the Pallas work must leave
    byte-identical."""
    import numpy as np

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.parallel.keyed import KeyedAlignedPipeline

    p = KeyedAlignedPipeline(
        [TumblingWindow(WindowMeasure.Time, 50)], [SumAggregation()],
        n_keys=4,
        config=EngineConfig(capacity=1 << 10, batch_size=64,
                            annex_capacity=64, min_trigger_pad=32),
        throughput=4 * 4000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9)
    p.reset()
    return p._step.lower(p.state, p._interval_key(0), np.int64(0))


def _aligned_pallas_lowered(window_ms: int = 50):
    """Flagged-ON canonical aligned step (ISSUE 15): the SAME tiny
    lineage config as the default-off aligned pin, with the Pallas
    segmented-reduce fold enabled — so the Pallas lowering carries its
    own pinned lineage next to the default-off pin, and drift in
    either is independently red/green."""
    import numpy as np

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [TumblingWindow(WindowMeasure.Time, window_ms)],
        [SumAggregation()],
        config=EngineConfig(capacity=1 << 12, batch_size=256,
                            annex_capacity=256, min_trigger_pad=32,
                            pallas_slice_merge=True),
        throughput=20_000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9, value_scale=1024.0)
    p.reset()
    return p._step.lower(p.state, p.dm, p._interval_key(0), np.int64(0))


def _aligned_microbatch_lowered():
    """Flagged-ON canonical micro-batched flush (ISSUE 15): the aligned
    lineage config at ``micro_batch=2`` — pins the flush program
    (reduce + append + trigger/query) of the streamed-emission path."""
    import numpy as np

    import jax

    from scotty_tpu import SumAggregation, TumblingWindow, WindowMeasure
    from scotty_tpu.engine import EngineConfig
    from scotty_tpu.engine.pipeline import AlignedStreamPipeline

    p = AlignedStreamPipeline(
        [TumblingWindow(WindowMeasure.Time, 50)], [SumAggregation()],
        config=EngineConfig(capacity=1 << 12, batch_size=256,
                            annex_capacity=256, min_trigger_pad=32,
                            micro_batch=2),
        throughput=20_000, wm_period_ms=100, max_lateness=100, seed=5,
        gc_every=10 ** 9, value_scale=1024.0)
    p.reset()
    p.micro_start(0)
    return p._micro_flush_fn.lower(
        p.state, p.dm, p._micro_slab, p._micro_key, p._micro_iv)


def _sort_split_pallas_lowered():
    """Canonical Pallas sort-split lowering (ISSUE 15): the bucketed
    bitonic kernel at a tiny power-of-two batch — the lowering every
    flagged shaped batch dispatches (per-shape; this pins the
    construction's lineage)."""
    import numpy as np

    import jax

    from scotty_tpu.pallas import build_pallas_sort_split
    from scotty_tpu.shaper.device import init_shaper_stats

    B, L = 256, 64
    kern = jax.jit(build_pallas_sort_split(B, L), donate_argnums=0)
    stats = init_shaper_stats()
    ts = np.arange(B, dtype=np.int64)
    vals = np.zeros(B, np.float32)
    valid = np.ones(B, bool)
    return kern.lower(stats, ts, vals, valid, np.int64(0), np.int64(0),
                      np.int64(0))


#: the pinned step configs; insertion order is the report order
CANONICAL_STEPS = {
    "aligned": _aligned_lowered,
    "session": _session_lowered,
    "count": _count_lowered,
    "context": _context_lowered,
    "mesh": _mesh_lowered,
    "mesh_serving": _mesh_serving_lowered,
    "keyed": _keyed_lowered,
    # flagged-ON Pallas / micro-batch lineages (ISSUE 15) — pinned next
    # to the default-off pins so both drift independently
    "aligned_pallas": _aligned_pallas_lowered,
    "aligned_microbatch": _aligned_microbatch_lowered,
    "sort_split_pallas": _sort_split_pallas_lowered,
}


def lowered_hash(lowered) -> str:
    """sha256 of ``lowered.as_text()`` — the exact hand-run recipe."""
    return hashlib.sha256(lowered.as_text().encode()).hexdigest()


def step_hash(name: str, **kwargs) -> str:
    """Hash one canonical step config (kwargs reach the builder — the
    mutation test passes ``window_ms=100`` to prove a changed config
    fails the pin)."""
    return lowered_hash(CANONICAL_STEPS[name](**kwargs))


def compute_pins(names: Optional[Sequence[str]] = None) -> Dict[str, str]:
    return {n: step_hash(n) for n in (names or CANONICAL_STEPS)}


def pins_path(root=None) -> pathlib.Path:
    return pathlib.Path(root or default_root()) / DEFAULT_PINS_PATH


def load_pins(path=None) -> Dict[str, str]:
    p = pathlib.Path(path or pins_path())
    doc = json.loads(p.read_text())
    if not str(doc.get("schema", "")).startswith("scotty_tpu.hlo_pins/"):
        raise ValueError(f"{p}: not an hlo-pins file "
                         f"(schema={doc.get('schema')!r})")
    return doc["pins"]


def write_pins(pins: Dict[str, str], path=None) -> None:
    p = pathlib.Path(path or pins_path())
    p.write_text(json.dumps(
        {"schema": PINS_SCHEMA, "pins": pins}, indent=1) + "\n")
