"""``donation-safety`` — the ISSUE 2 checkpoint-restore bug class,
statically.

The engine's fused steps donate their carried state
(``jax.jit(step, donate_argnums=...)``): XLA recycles the input buffer
for the output, so (a) the donated argument is DEAD after the call —
reading it again observes recycled memory — and (b) a numpy-backed
(CPU zero-copy) leaf fed to a donating kernel lets XLA recycle host
memory that live result handles still alias. Class (b) is exactly the
ISSUE 2 incident: checkpoint restores fed ``np.load``-backed leaves to
donating kernels and produced garbled resumed window bounds in one test
and a segfault mid-step in another; the fix (``utils/checkpoint.py
_device_copy``) materializes XLA-owned copies first.

Per module, the rule:

1. collects donating bindings — ``<name> = jax.jit(fn,
   donate_argnums=<literal>)`` assigned to a plain name or a
   ``self.<attr>`` (conditional expressions contribute the union of
   their branches' donated positions);
2. at every call of a collected binding, resolves the donated
   positional arguments that are plain names or ``self.<attr>`` chains
   and flags
   **use-after-donation** — a later read of that name in the same
   function body before it is reassigned — and
   **host-backed-leaf** — an argument whose nearest preceding
   assignment in the function is a bare numpy constructor
   (``np.zeros/array/asarray/full/arange/copy/load``) or
   ``jax.device_get``, i.e. host memory handed to a donating kernel
   (route it through ``jax.device_put`` / checkpoint ``_device_copy``
   first).

The analysis is intraprocedural and name-based by design: it catches
the review-visible shape of both incidents without a dataflow engine,
and the differential tests remain the dynamic backstop.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ..core import Rule, SourceFile, register

_NP_CTORS = ("zeros", "ones", "empty", "full", "array", "asarray",
             "arange", "copy", "load", "frombuffer")


def _donated_positions(call: ast.Call) -> Optional[Set[int]]:
    """Donated argnums of a ``jax.jit`` call, or None if not one."""
    f = call.func
    if not (isinstance(f, ast.Attribute) and f.attr == "jit"
            and isinstance(f.value, ast.Name) and f.value.id == "jax"):
        return None
    for kw in call.keywords:
        if kw.arg != "donate_argnums":
            continue
        v = kw.value
        if isinstance(v, ast.Constant) and isinstance(v.value, int):
            return {v.value}
        if isinstance(v, (ast.Tuple, ast.List)):
            out = set()
            for e in v.elts:
                if isinstance(e, ast.Constant) \
                        and isinstance(e.value, int):
                    out.add(e.value)
            return out
    return None


def _jit_bindings_in(value: ast.AST) -> Optional[Set[int]]:
    """Donated positions contributed by an assignment's value —
    handles the bare call and conditional-expression forms
    (``jax.jit(...) if cond else jax.jit(...)``: union)."""
    if isinstance(value, ast.Call):
        return _donated_positions(value)
    if isinstance(value, ast.IfExp):
        a = _jit_bindings_in(value.body)
        b = _jit_bindings_in(value.orelse)
        if a is None and b is None:
            return None
        return (a or set()) | (b or set())
    return None


def _binding_name(target: ast.AST) -> Optional[str]:
    """The registry key for an assignment target: ``"name"`` for a
    plain Name, ``".attr"`` for ``self.<attr>`` (leading dot marks the
    attribute namespace)."""
    if isinstance(target, ast.Name):
        return target.id
    if isinstance(target, ast.Attribute) \
            and isinstance(target.value, ast.Name) \
            and target.value.id == "self":
        return "." + target.attr
    return None


def _ref_key(expr: ast.AST) -> Optional[str]:
    """Same key space for a call-argument expression."""
    return _binding_name(expr)


def _reads(node: ast.AST, key: str) -> bool:
    """Does ``node`` read (Load) the name/attr ``key`` anywhere?"""
    for n in ast.walk(node):
        if key.startswith("."):
            if (isinstance(n, ast.Attribute) and n.attr == key[1:]
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and isinstance(n.ctx, ast.Load)):
                return True
        else:
            if (isinstance(n, ast.Name) and n.id == key
                    and isinstance(n.ctx, ast.Load)):
                return True
    return False


def _stores(stmt: ast.AST, key: str) -> bool:
    """Does statement ``stmt`` assign ``key`` (including tuple targets
    and ``for`` targets)?"""
    for n in ast.walk(stmt):
        if key.startswith("."):
            if (isinstance(n, ast.Attribute) and n.attr == key[1:]
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"
                    and isinstance(n.ctx, ast.Store)):
                return True
        else:
            if (isinstance(n, ast.Name) and n.id == key
                    and isinstance(n.ctx, ast.Store)):
                return True
    return False


def _is_host_backed(value: ast.AST) -> bool:
    """Is this assignment value a bare numpy constructor or a
    ``jax.device_get`` — i.e. host memory?"""
    if not isinstance(value, ast.Call):
        return False
    f = value.func
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name):
        if f.value.id in ("np", "numpy") and f.attr in _NP_CTORS:
            return True
        if f.value.id == "jax" and f.attr == "device_get":
            return True
    return False


def _inline_np_ctor(expr: ast.AST) -> bool:
    """Argument IS a direct ``np.<ctor>(...)`` call."""
    if not isinstance(expr, ast.Call):
        return False
    f = expr.func
    return (isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy") and f.attr in _NP_CTORS)


def _child_blocks(stmt: ast.stmt) -> Iterable[List[ast.stmt]]:
    """The statement blocks nested in a compound statement (nested
    function/class definitions are separate scopes, not control flow)."""
    if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return
    for name in ("body", "orelse", "finalbody"):
        blk = getattr(stmt, name, None)
        if blk:
            yield blk
    for handler in getattr(stmt, "handlers", []) or []:
        yield handler.body


def _stmts_with_successors(body: List[ast.stmt], inherited=()):
    """Yield ``(stmt, successors)`` for every statement reachable from
    ``body``, where ``successors`` is the ordered list of WHOLE
    statements that can execute after it: the rest of its own block,
    then its ancestors' followers. Sibling branches of the same
    ``if``/``try`` are NOT each other's successors — that's the point
    (a linear flattening flags branch A's donation against branch B's
    read)."""
    inherited = list(inherited)
    for i, stmt in enumerate(body):
        succ = body[i + 1:] + inherited
        yield stmt, succ
        for blk in _child_blocks(stmt):
            yield from _stmts_with_successors(blk, succ)


@register
class DonationSafety(Rule):
    name = "donation-safety"
    doc = ("donated args read after a donating-kernel call, or "
           "numpy/host-backed leaves fed to donating kernels — the "
           "ISSUE 2 restore-segfault class")
    include = ("scotty_tpu", "tests")

    def check(self, src: SourceFile):
        # pass 1: donating bindings in this module (name → positions)
        donating: Dict[str, Set[int]] = {}
        for node in src.walk:
            if isinstance(node, ast.Assign):
                pos = _jit_bindings_in(node.value)
                if pos is None:
                    continue
                for t in node.targets:
                    key = _binding_name(t)
                    if key is not None:
                        donating[key] = donating.get(key, set()) | pos
        if not donating:
            return

        # pass 2: per function, examine calls of donating bindings.
        # Only SIMPLE statements host examined calls (a donating call in
        # an if/while header is not an idiom this codebase has) — a
        # compound statement's calls are found when its inner simple
        # statements are visited, so nothing is double-reported.
        for fn in src.walk:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            preceding: List[ast.stmt] = []
            for stmt, succ in _stmts_with_successors(fn.body):
                if isinstance(stmt, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign, ast.Expr,
                                     ast.Return)):
                    for call in ast.walk(stmt):
                        if not isinstance(call, ast.Call):
                            continue
                        key = _call_key(call)
                        if key is None or key not in donating:
                            continue
                        yield from self._check_call(
                            src, preceding, succ, stmt, call,
                            donating[key])
                preceding.append(stmt)

    def _check_call(self, src, preceding, successors, stmt, call,
                    positions):
        for pos in sorted(positions):
            if pos >= len(call.args):
                continue
            arg = call.args[pos]
            if _inline_np_ctor(arg):
                yield self.finding(
                    self.name, src, call,
                    f"numpy-backed leaf (arg {pos}) fed directly to a "
                    "donating kernel — XLA will recycle host memory "
                    "that live handles may alias; materialize via "
                    "jax.device_put first")
                continue
            key = _ref_key(arg)
            if key is None:
                continue
            # host-backed taint: the NEAREST preceding assignment wins
            # (a later device_put/_device_copy rebind clears it)
            taint = None
            for prev in preceding:
                if isinstance(prev, ast.Assign) \
                        and any(_binding_name(t) == key
                                for t in prev.targets):
                    taint = prev if _is_host_backed(prev.value) \
                        else None
            if taint is not None:
                yield self.finding(
                    self.name, src, call,
                    f"'{key.lstrip('.')}' (arg {pos}) is numpy/host-"
                    f"backed (assigned at line {taint.lineno}) and "
                    "flows into a donating kernel — the ISSUE 2 "
                    "restore-segfault class; materialize an XLA-owned "
                    "copy (jax.device_put / checkpoint._device_copy) "
                    "first")
            # use-after-donation: the same statement may reassign the
            # arg (the carry idiom `self.state, res = self._step(
            # self.state, ...)`); if it does, the donation is safe
            if _stores(stmt, key):
                continue
            for later in successors:
                if _stores(later, key) and not _reads(later, key):
                    break
                if _reads(later, key):
                    yield self.finding(
                        self.name, src, later,
                        f"'{key.lstrip('.')}' read after being donated "
                        f"to a kernel at line {call.lineno} — the "
                        "buffer was recycled by XLA; rebind it from "
                        "the call's result or drop the read")
                    break


def _call_key(call: ast.Call) -> Optional[str]:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
            and f.value.id == "self":
        return "." + f.attr
    return None
