"""``silent-drop`` — broad exception handlers in the data path must
leave evidence.

The delivery/ingest/connector layers own the exactly-once and
tuple-conservation invariants (ISSUES 7/8): every record is delivered,
shed (counted), dead-lettered (counted), or the run fails. A bare
``except:`` / ``except Exception:`` that neither re-raises nor
increments a counter / records a flight event is a hole in that
accounting — the soak audit's conservation identity can't see what the
handler swallowed. (The kafka ``_default_deserialize`` crash that
ISSUE 3 dead-lettered, and the poison/dead-letter machinery itself,
exist precisely because swallowing was the previous failure mode.)

Narrow handlers (``except StopAsyncIteration:`` etc.) pass — typed
control flow is fine; only ``except:``, ``except Exception:`` and
``except BaseException:`` with an inert body are flagged. "Evidence"
in the body = a ``raise``, a ``return``/propagation of the error
object, or a call to ``inc`` / ``observe`` / ``flight_event`` /
``record`` / ``record_failure`` / ``handle`` / a dead-letter hook.
Crash-path side channels that deliberately swallow (a postmortem
writer must never mask the original failure) carry inline
suppressions saying so.
"""

from __future__ import annotations

import ast

from ..core import Rule, SourceFile, register

#: method calls (Attribute form only — matching bare names here would
#: let the builtin ``set()``/``dict.record`` collide) that count as
#: evidence: counter/gauge moves, flight recording, dead-lettering, the
#: poison handler, loggers, and the supervised-recovery handlers
#: (handle_failure/_backoff flight-record and count resilience_restarts
#: before deciding to retry or give up)
_EVIDENCE_METHODS = frozenset({
    "inc", "observe", "set", "flight_event", "record", "record_failure",
    "handle", "dead_letter", "warning", "error", "exception",
    "handle_failure", "_backoff",
})
#: bare-function forms that are unambiguous evidence (module-level
#: helpers, not shadowable builtins)
_EVIDENCE_FUNCTIONS = frozenset({
    "flight_event", "record_failure", "dead_letter", "handle_failure",
})


def _is_broad(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _leaves_evidence(handler: ast.ExceptHandler) -> bool:
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return True
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in _EVIDENCE_METHODS:
                return True
            if isinstance(f, ast.Name) and f.id in _EVIDENCE_FUNCTIONS:
                return True
        if isinstance(node, ast.Return) and node.value is not None:
            return True
    return False


@register
class SilentDrop(Rule):
    name = "silent-drop"
    doc = ("bare/broad except that neither re-raises nor counts in the "
           "data-path packages — swallowed errors break the "
           "tuple-conservation accounting")
    include = ("scotty_tpu/connectors", "scotty_tpu/ingest",
               "scotty_tpu/delivery", "scotty_tpu/resilience",
               "scotty_tpu/soak", "scotty_tpu/obs")

    def check(self, src: SourceFile):
        for node in src.walk:
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not _is_broad(node) or _leaves_evidence(node):
                continue
            yield self.finding(
                self.name, src, node,
                "broad except swallows the error without evidence — "
                "re-raise, dead-letter, or count it (counter inc / "
                "flight event) so the conservation audit can see it")
