"""``geometry-discipline`` — coupled retunable knobs derive from one
:class:`~scotty_tpu.autotune.EngineGeometry`, never co-constructed raw
(the ISSUE 18 config refactor's inverse guard).

The engine's tuning surface lives in one frozen value: ``EngineGeometry``
keys the warm-step cache, commits as the ``geometry.json`` checkpoint
sidecar, and is what ``apply_geometry`` moves atomically. A function
that hand-builds two or more of :class:`~scotty_tpu.engine.config.
EngineConfig` / :class:`~scotty_tpu.shaper.ShaperConfig` /
:class:`~scotty_tpu.ingest.RingConfig` with retunable kwargs has
re-scattered that surface — its knobs can drift apart (a batch size the
ring's block no longer matches, a late lane sized for a different batch
span), and the resulting engine runs at a geometry no sidecar or cache
key describes. Derive instead::

    geom = EngineGeometry(capacity=..., batch_size=..., late_capacity=...)
    op = TpuWindowOperator(config=geom.engine_config(base))
    shaper = StreamShaper(op, geom.shaper_config())

A single config class with retunable kwargs is fine (nothing to couple);
non-retunable kwargs (overflow policy, annex capacity, routing, dtypes)
never count — their source of truth stays the per-module config.
"""

from __future__ import annotations

import ast

from ..core import Rule, SourceFile, register

#: config class -> the kwargs EngineGeometry owns (the retunable knobs;
#: passing any of these marks the construction as geometry-carrying)
RETUNABLE_KWARGS = {
    "EngineConfig": frozenset({
        "capacity", "batch_size", "min_trigger_pad", "micro_batch",
        "pallas_sort_split", "pallas_slice_merge", "pallas_packed"}),
    "ShaperConfig": frozenset({
        "slack_ms", "late_capacity", "pallas_sort_split"}),
    "RingConfig": frozenset({"depth", "block_size"}),
}


def _config_call(node: ast.Call):
    """(class name, offending retunable kwargs) for a retunable-knob
    config construction, else None."""
    f = node.func
    name = f.id if isinstance(f, ast.Name) else (
        f.attr if isinstance(f, ast.Attribute) else None)
    if name not in RETUNABLE_KWARGS:
        return None
    knobs = {kw.arg for kw in node.keywords
             if kw.arg} & RETUNABLE_KWARGS[name]
    return (name, knobs) if knobs else None


@register
class GeometryDiscipline(Rule):
    name = "geometry-discipline"
    doc = ("two or more config classes (EngineConfig/ShaperConfig/"
           "RingConfig) hand-built with retunable kwargs in one "
           "function — derive them from a single EngineGeometry so the "
           "coupled knobs cannot drift apart")
    include = ("scotty_tpu",)
    #: the geometry's own derivation methods necessarily construct the
    #: per-module configs
    exclude = ("scotty_tpu/autotune/",)

    def check(self, src: SourceFile):
        for fn in src.walk:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            hits = []                 # (class name, knobs, call node)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    hit = _config_call(node)
                    if hit is not None:
                        hits.append((hit[0], hit[1], node))
            if len({h[0] for h in hits}) < 2:
                continue
            for cls_name, knobs, node in hits:
                yield self.finding(
                    self.name, src, node,
                    f"{cls_name}({', '.join(sorted(knobs))}=...) "
                    f"co-constructed with other retunable configs in "
                    f"{fn.name}() — derive both from one EngineGeometry "
                    "(geometry.engine_config()/shaper_config()/"
                    "ring_config()) so the coupled knobs move as a "
                    "single cacheable, sidecar-committable value")
