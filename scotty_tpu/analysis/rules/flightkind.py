"""``flight-kind`` — event kinds passed to flight recording are
:mod:`scotty_tpu.obs.flight` constants, never string literals (the
ISSUE 6 review finding).

``obs postmortem`` classifies crash causes by matching on the kind
vocabulary; a typo'd literal kind (``"overlow"``) records events the
triage CLI silently fails to classify, and a literal that drifts from
the constant's value splits one event family across two names. The
ISSUE 6 review pass fixed the operator/connector sites by hand; this
rule pins the invariant for every site.

Flagged call shapes (the kind argument must not be a plain string
constant — a Name/Attribute that resolves to the constant, or a
variable, passes):

* ``<obs>.flight_event(kind, name[, value])``
* ``<obs>.record_failure(exc, kind=...)``
* ``<...>.flight.record(kind, ...)`` (the raw recorder)
"""

from __future__ import annotations

import ast

from ..core import Rule, SourceFile, register


def _literal_kind(call: ast.Call):
    """The offending string-literal kind argument, or None."""
    f = call.func
    if not isinstance(f, ast.Attribute):
        return None
    if f.attr == "flight_event":
        kind = call.args[0] if call.args else None
    elif f.attr == "record_failure":
        kind = None
        for kw in call.keywords:
            if kw.arg == "kind":
                kind = kw.value
        if kind is None and len(call.args) >= 2:
            kind = call.args[1]
    elif f.attr == "record" and (
            (isinstance(f.value, ast.Attribute)
             and f.value.attr == "flight")
            or (isinstance(f.value, ast.Name)
                and f.value.id == "flight")):
        kind = call.args[0] if call.args else None
    else:
        return None
    if isinstance(kind, ast.Constant) and isinstance(kind.value, str):
        return kind
    return None


@register
class FlightKindRegistry(Rule):
    name = "flight-kind"
    doc = ("string-literal event kinds at flight-recording call sites — "
           "use the obs.flight constants so postmortem classification "
           "and the kind vocabulary cannot drift")
    include = ("scotty_tpu",)
    #: the vocabulary's defining module may spell its own constants
    exclude = ("scotty_tpu/obs/flight.py",)

    def check(self, src: SourceFile):
        for node in src.walk:
            if not isinstance(node, ast.Call):
                continue
            kind = _literal_kind(node)
            if kind is None:
                continue
            yield self.finding(
                self.name, src, node,
                f"string-literal flight-event kind {kind.value!r} — "
                "use the scotty_tpu.obs.flight constant (obs "
                "postmortem matches on this vocabulary; literals "
                "drift)")
