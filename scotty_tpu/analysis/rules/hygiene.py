"""Engine-silence and clock-discipline rules — the three grown-by-
accretion walkers from tests/test_no_print_in_engine.py (ISSUES 1/3/4/7
satellites) as registry rules with one shared call matcher. Extending a
scope is now a one-line change to the rule class instead of a
copy-pasted directory list.

* ``no-print`` — the reference's engine never logs (SURVEY.md §5); all
  output flows through the obs registry / overridable echo sinks
  (``scotty_tpu.utils.stdout_echo``), never a bare ``print(`` — bench
  and CLI output in particular must stay capturable so the ``obs diff``
  gate and tests can consume it. Scope: the ENTIRE package (the old
  test listed eight directories; obs/bench CLIs already route through
  echo sinks).
* ``no-sleep`` — every wait goes through the injectable
  :mod:`scotty_tpu.resilience.clock` (the one exempt module), so chaos
  tests drive backoff/watchdog logic deterministically on a
  ManualClock.
* ``no-wall-clock`` — the obs/ingest/soak/delivery layers never read
  ``time.time()``/``time.monotonic()`` directly: export timestamps and
  soak pace/audit reads come from ``resilience.clock`` (``wall_time`` /
  the injectable Clock) so bundle timelines stay deterministic.
  ``time.perf_counter`` (relative span durations) stays allowed.
"""

from __future__ import annotations

import ast

from ..core import Rule, SourceFile, register


def _calls(src: SourceFile, names=(), attrs=()):
    """Shared matcher: yield Call nodes whose func is a bare Name in
    ``names`` or a ``<mod>.<attr>`` Attribute with attr in ``attrs``
    (any receiver — ``from time import sleep`` aliases are caught by
    the Name arm)."""
    for node in src.walk:
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in names:
            yield node
        elif (isinstance(f, ast.Attribute) and f.attr in attrs
                and isinstance(f.value, ast.Name)):
            yield node


@register
class NoPrint(Rule):
    name = "no-print"
    doc = ("bare print( anywhere in scotty_tpu — route output through "
           "the obs registry or an overridable echo sink "
           "(utils.stdout_echo)")
    include = ("scotty_tpu",)

    def check(self, src: SourceFile):
        for node in _calls(src, names=("print",)):
            yield self.finding(
                self.name, src, node,
                "bare print( — route output through the scotty_tpu.obs "
                "registry or an overridable echo sink "
                "(scotty_tpu.utils.stdout_echo)")


@register
class NoSleep(Rule):
    name = "no-sleep"
    doc = ("bare time.sleep outside resilience/clock.py — waits go "
           "through the injectable Clock so chaos tests stay "
           "deterministic")
    include = ("scotty_tpu",)
    #: SystemClock's implementation — the single sanctioned sleep site
    exclude = ("scotty_tpu/resilience/clock.py",)

    def check(self, src: SourceFile):
        for node in _calls(src, names=("sleep",), attrs=("sleep",)):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.value.id not in ("time",)):
                continue        # clock.sleep / asyncio.sleep are fine
            yield self.finding(
                self.name, src, node,
                "bare time.sleep — route waits through "
                "scotty_tpu.resilience.clock (injectable Clock)")


@register
class NoWallClock(Rule):
    name = "no-wall-clock"
    doc = ("bare time.time()/time.monotonic() in obs/ingest/soak/"
           "delivery — timestamps come from resilience.clock "
           "(wall_time / the injectable Clock)")
    include = ("scotty_tpu/obs", "scotty_tpu/ingest", "scotty_tpu/soak",
               "scotty_tpu/delivery", "scotty_tpu/pallas")

    def check(self, src: SourceFile):
        for node in _calls(src, names=("time", "monotonic"),
                           attrs=("time", "monotonic")):
            f = node.func
            if (isinstance(f, ast.Attribute)
                    and f.value.id not in ("time",)):
                continue        # clock.time()-style receivers are fine
            yield self.finding(
                self.name, src, node,
                "bare wall-clock read — use scotty_tpu.resilience.clock "
                "(wall_time for export rows, the injectable Clock for "
                "event time) so ManualClock tests stay deterministic")
