"""``metric-coherence`` — every metric name the gates and docs promise
must resolve to a name the code can actually create.

Three surfaces reference counters by name: the ``obs diff``
DEFAULT_THRESHOLDS gate (a typo'd key silently gates NOTHING — the
regression it was meant to catch sails through), the ``/metrics``
endpoint documentation, and the docs/API.md + README metric tables.
The registry itself is stringly-typed and lazily created, so nothing
at runtime ever cross-checks these — this rule does it statically.

The name universe is built from the package sources: every
metric-shaped string literal (exact names like
``"resilience_shed_tuples"``) plus the literal prefixes of dynamic
f-string names (``f"device_late_age_ms_le_{e}"`` contributes
``device_late_age_ms_le_``). Checked against it:

* every key of the ``metrics`` dict inside ``DEFAULT_THRESHOLDS``
  (parsed from obs/diff.py's AST, never imported);
* every metric-family token in the docs
  (``(device|resilience|shaper|serving|ingest_ring|soak|delivery|
  ckpt|flight|health|slo)_…`` — the prefixed families are where
  doc drift happens; placeholder spellings like
  ``serving_tenant_active_<tenant>`` resolve via the f-string
  prefixes).
"""

from __future__ import annotations

import ast
import re
from typing import Set, Tuple

from ..core import Finding, Project, Rule, register

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]{3,}$")
_TOKEN_RE = re.compile(r"[a-z][a-z0-9_]{3,}")
_DOC_METRIC_RE = re.compile(
    r"\b((?:device|resilience|shaper|serving|ingest_ring|soak|delivery"
    r"|ckpt|flight|health|latency|workload|costmodel|slo)_[a-z0-9_]+)")


def _universe(project: Project) -> Tuple[Set[str], Set[str]]:
    """(exact names, dynamic prefixes) from every package source."""
    exact: Set[str] = set()
    prefixes: Set[str] = set()
    for src in project.sources.values():
        if not src.rel.startswith("scotty_tpu/"):
            continue
        if src.rel.endswith("/diff.py"):
            # the thresholds module must not anchor its OWN keys —
            # a typo'd gate key would resolve against itself and the
            # check would be vacuous
            continue
        for node in src.walk:
            if isinstance(node, ast.Constant) \
                    and isinstance(node.value, str):
                if _NAME_RE.match(node.value):
                    exact.add(node.value)
                else:
                    # names embedded in larger literals ("soak_report.
                    # json", format strings) still anchor doc tokens
                    exact.update(_TOKEN_RE.findall(node.value))
            elif isinstance(node, (ast.FunctionDef,
                                   ast.AsyncFunctionDef, ast.ClassDef)):
                # docs also reference API identifiers that happen to
                # match the metric families (flight_sync, ckpt_dir):
                # any defined name/arg/attribute anchors a doc token
                exact.add(node.name)
            elif isinstance(node, ast.Attribute):
                exact.add(node.attr)
            elif isinstance(node, ast.Name):
                exact.add(node.id)
            elif isinstance(node, ast.arg):
                exact.add(node.arg)
            elif isinstance(node, ast.keyword) and node.arg:
                exact.add(node.arg)
            elif isinstance(node, ast.JoinedStr) and node.values:
                head = node.values[0]
                if isinstance(head, ast.Constant) \
                        and isinstance(head.value, str) \
                        and _TOKEN_RE.match(head.value):
                    prefixes.add(head.value)
    return exact, prefixes


def _resolves(name: str, exact: Set[str], prefixes: Set[str]) -> bool:
    if name in exact:
        return True
    return any(name.startswith(p) and len(name) > len(p)
               for p in prefixes if len(p) >= 6)


def _threshold_keys(project: Project):
    """(key, lineno) pairs of DEFAULT_THRESHOLDS["metrics"] parsed from
    obs/diff.py — AST only, so the check needs no imports."""
    src = project.sources.get("scotty_tpu/obs/diff.py")
    if src is None:
        for rel, s in project.sources.items():
            if rel.endswith("/diff.py") or rel == "diff.py":
                src = s
                break
    if src is None:
        return None, []
    for node in src.walk:
        if isinstance(node, ast.Assign) \
                and any(isinstance(t, ast.Name)
                        and t.id == "DEFAULT_THRESHOLDS"
                        for t in node.targets) \
                and isinstance(node.value, ast.Dict):
            for k, v in zip(node.value.keys, node.value.values):
                if isinstance(k, ast.Constant) and k.value == "metrics" \
                        and isinstance(v, ast.Dict):
                    return src, [
                        (mk.value, mk.lineno)
                        for mk in v.keys
                        if isinstance(mk, ast.Constant)
                        and isinstance(mk.value, str)]
    return src, []


@register
class MetricCoherence(Rule):
    name = "metric-coherence"
    doc = ("obs-diff threshold keys and docs metric references that "
           "resolve to no counter the code creates — a typo'd gate "
           "gates nothing")

    def check_project(self, project: Project):
        exact, prefixes = _universe(project)
        if not exact:
            return
        src, keys = _threshold_keys(project)
        for key, lineno in keys:
            if not _resolves(key, exact, prefixes):
                yield Finding(
                    rule=self.name, path=src.rel, line=lineno,
                    message=f"DEFAULT_THRESHOLDS gates {key!r} but no "
                            "code creates a metric of that name — the "
                            "gate silently never fires",
                    snippet=src.line_at(lineno))
        for doc_rel, text in project.docs.items():
            for i, line in enumerate(text.splitlines(), start=1):
                for m in _DOC_METRIC_RE.finditer(line):
                    token = m.group(1)
                    if not _resolves(token, exact, prefixes):
                        yield Finding(
                            rule=self.name, path=doc_rel, line=i,
                            message=f"docs reference metric {token!r} "
                                    "but no code creates it — doc "
                                    "drift or a typo",
                            snippet=line.strip())
