"""The rule set. Importing this package populates
:data:`scotty_tpu.analysis.core.RULES`; each module groups one invariant
family and names the incident that motivated it (docs/API.md "Static
analysis" carries the full catalog)."""

from . import (  # noqa: F401
    coherence,
    donation,
    flightkind,
    fsio_rule,
    geometry_discipline,
    hostsync,
    hygiene,
    silentdrop,
)
