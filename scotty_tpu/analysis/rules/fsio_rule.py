"""``fsio-discipline`` — committed bytes flow through
:mod:`scotty_tpu.utils.fsio` (the ISSUE 8 bug class).

ISSUE 8's review passes found three state-file paths by hand that wrote
around the fault-injectable shim (keyed_connector.pkl, the orbax-path
meta.json, serving's query_table.json): a silent short write of any of
them was blessed into the checkpoint manifest by the disk-bytes
fallback, and restore then crash-looped. The invariant: every byte a
checkpoint/ledger/commit path puts on disk goes through
``fsio.write_bytes``/``fsio.replace`` so (a) the intent digest lands in
the manifest and (b) the crash-point fuzzer can interpose on the op.

The rule flags the raw primitives — ``open(..., "w"/"a"/"x"/"+")``,
``json.dump``/``pickle.dump`` (the file-object forms; ``dumps`` is
fine), ``np.save*``, ``os.replace``/``os.rename``, ``shutil.move`` —
everywhere in the package except ``bench/`` (bench results are reports,
not committed state) and ``utils/fsio.py`` itself (the implementation).
Telemetry exports and crash-path writers that deliberately bypass the
shim carry inline suppressions stating why.
"""

from __future__ import annotations

import ast

from ..core import Rule, SourceFile, register

_NP_WRITERS = ("save", "savez", "savez_compressed", "savetxt")
_WRITE_MODES = ("w", "a", "x", "+")


def _open_mode(node: ast.Call):
    """The mode literal of an ``open(...)`` call, or None."""
    if len(node.args) >= 2 and isinstance(node.args[1], ast.Constant) \
            and isinstance(node.args[1].value, str):
        return node.args[1].value
    for kw in node.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant) \
                and isinstance(kw.value.value, str):
            return kw.value.value
    return None


@register
class FsioDiscipline(Rule):
    name = "fsio-discipline"
    doc = ("raw file writes (open-for-write / json.dump / pickle.dump / "
           "np.save* / os.replace) outside utils.fsio — committed bytes "
           "must record intent digests and stay crash-fuzzable")
    include = ("scotty_tpu",)
    exclude = (
        # bench results are reports, not committed state
        "scotty_tpu/bench",
        # the sanctioned implementation of the discipline itself
        "scotty_tpu/utils/fsio.py",
    )

    def check(self, src: SourceFile):
        for node in src.walk:
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            msg = None
            if isinstance(f, ast.Name) and f.id == "open":
                mode = _open_mode(node)
                if mode and any(c in mode for c in _WRITE_MODES):
                    msg = (f"open(..., {mode!r}) writes around "
                           "utils.fsio — use fsio.write_bytes so the "
                           "intent digest is recorded and the "
                           "crash-point fuzzer can interpose")
            elif isinstance(f, ast.Attribute) \
                    and isinstance(f.value, ast.Name):
                recv, attr = f.value.id, f.attr
                if recv in ("json", "pickle") and attr == "dump":
                    msg = (f"{recv}.dump to a file object bypasses "
                           "utils.fsio — serialize with "
                           f"{recv}.dumps and commit via "
                           "fsio.write_bytes")
                elif recv in ("np", "numpy") and attr in _NP_WRITERS:
                    msg = (f"np.{attr} writes around utils.fsio — "
                           "serialize to a buffer and commit via "
                           "fsio.write_bytes")
                elif recv == "os" and attr in ("replace", "rename"):
                    msg = (f"os.{attr} is a commit point — use "
                           "fsio.replace so the flip is "
                           "crash-fuzzable and durable (dir fsyncs)")
                elif recv == "shutil" and attr == "move":
                    msg = ("shutil.move is a commit point — use "
                           "fsio.replace")
            if msg:
                yield self.finding(self.name, src, node, msg)
