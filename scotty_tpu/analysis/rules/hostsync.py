"""``host-sync`` — device round trips only at sanctioned drain points.

The engine's performance story (PAPER.md, SURVEY §2) rests on fused
steps that dispatch asynchronously with ZERO host syncs; a stray
``device_get``/``block_until_ready``/``.item()`` in a hot path turns a
66 G t/s pipeline into a per-interval round trip. Every legitimate sync
in the jitted-path packages lives in a named drain-point function
(``sync``, ``check_overflow``, the ``materialize_*`` replay faces, …)
— this rule pins that set, so a new sync site is a red check the author
must either move to a drain point or allowlist explicitly here (with
review seeing the diff).

The dynamic complement is ``jax.transfer_guard("disallow")`` wrapped
around the differential tests' step invocations
(tests/test_pipeline.py etc.) — the rule catches the sites statically,
the guard proves the steps clean end-to-end.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..core import Rule, SourceFile, register

#: drain-point functions where a host round trip IS the contract:
#: the documented sync/drain faces (FusedPipelineDriver.sync,
#: check_overflow at every operator/pipeline), the host replay faces
#: (materialize_*, lower_*), the fetch-on-demand telemetry faces, and
#: the operator-internal refresh points that already ride a drain.
#: Extending this set is a one-line change — reviewed as such.
DRAIN_POINT_FUNCTIONS = frozenset({
    "sync", "check_overflow",
    "device_metrics", "device_stats",
    "lower_interval_columns", "lower_results", "lowered_results",
    "lowered_results_for_key",
    "materialize_interval", "materialize_interval_late",
    "_fetch_grid", "_fetch_sessions", "_pol_refresh", "_grow_capacity",
    "measure_link", "process_watermark_arrays_combined",
    # mesh-sharded keyed engine (ISSUE 10): the cross-shard global fold's
    # one result fetch, the all-fetched global lowering, and the
    # per-shard occupancy/overflow reads — each documented as riding the
    # same drain cadence as check_overflow
    "query_global", "lowered_global", "shard_occupancy",
    # mesh-serving control path (ISSUE 13): the per-key row-gather fetch
    # behind key_rows_by_slot (a device gather BEFORE the fetch, so
    # sampling keys never pulls the full [K, T] block) — documented as
    # riding the same drain cadence as lowered_global
    "per_key_columns",
    # micro-batched streamed emission (ISSUE 15): _fetch_streamed IS the
    # streamed drain (one interval's result fetch — the per-interval
    # analogue of sync()); micro_push's anchor fetch is the documented
    # arrival-pacing discipline (micro_pace, off by default);
    # micro_snapshot is a checkpoint boundary, like save/restore
    "_fetch_streamed", "micro_push", "micro_snapshot",
})

_SYNC_ATTRS = ("device_get", "block_until_ready", "item")


def _enclosing_function(src: SourceFile, node) -> Optional[str]:
    """Name of the innermost function containing ``node`` (by line
    span — the walk list carries no parent pointers)."""
    best = None
    best_span = None
    for n in src.walk:
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
            end = n.end_lineno or n.lineno
            if n.lineno <= node.lineno <= end:
                span = end - n.lineno
                if best_span is None or span < best_span:
                    best, best_span = n.name, span
    return best


@register
class HostSyncBan(Rule):
    name = "host-sync"
    doc = ("jax.device_get / block_until_ready / .item() outside the "
           "allowlisted drain-point functions in the jitted-path "
           "packages — syncs belong at documented drain points only")
    include = ("scotty_tpu/engine", "scotty_tpu/parallel",
               "scotty_tpu/shaper", "scotty_tpu/serving",
               "scotty_tpu/core", "scotty_tpu/mesh",
               "scotty_tpu/mesh_serving", "scotty_tpu/pallas")

    def check(self, src: SourceFile):
        for node in src.walk:
            if not isinstance(node, ast.Call):
                continue
            f = node.func
            if not (isinstance(f, ast.Attribute)
                    and f.attr in _SYNC_ATTRS):
                continue
            if f.attr == "item" and (node.args or node.keywords):
                continue        # dict.item-like APIs, not ndarray.item()
            fn = _enclosing_function(src, node)
            if fn in DRAIN_POINT_FUNCTIONS:
                continue
            yield self.finding(
                self.name, src, node,
                f"host sync ({f.attr}) outside a sanctioned drain point "
                f"(enclosing function: {fn or '<module>'}) — move it to "
                "a drain-point function or extend "
                "analysis.rules.hostsync.DRAIN_POINT_FUNCTIONS in a "
                "reviewed change")
