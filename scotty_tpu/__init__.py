"""scotty_tpu — a TPU-native stream window aggregation framework.

A from-scratch JAX/XLA re-design of the general stream slicing technique
(reference: the Scotty window processor, a JVM library — see SURVEY.md): the
stream is cut into non-overlapping slices, each slice holds one partial
aggregate per registered aggregation, and every concurrent window (tumbling /
sliding / session / fixed-band, time- or count-measured, thousands at once)
is answered by merging the partial aggregates of the slices it covers.

Architecture (TPU-first, not a port):

* ``core``      — window taxonomy + aggregation algebra (lift/combine/lower),
                  with vectorized faces for the device engine.
* ``state``     — pluggable state cells for the host path (checkpoint seam).
* ``simulator`` — full-fidelity host operator: correctness oracle and general
                  fallback, exact reference semantics.
* ``engine``    — the TPU path: slice ring buffers in HBM, batched ingest via
                  segment reductions, watermark triggering via closed-form
                  window enumeration + prefix-sum range queries.
* ``parallel``  — keys as a batch dimension; multi-chip scaling via
                  ``jax.sharding.Mesh`` + ``shard_map`` (keys are
                  embarrassingly parallel, exactly like the reference's
                  per-key operator partitioning).
* ``connectors``— thin adapters from host stream sources to the operator API.
* ``bench``     — config-driven throughput harness (JSON configs mirroring
                  the reference benchmark module).
"""

from .core import (
    AggregateFunction,
    AggregateWindow,
    CountAggregation,
    CountMinSketchAggregation,
    DDSketchQuantileAggregation,
    FixedBandWindow,
    HyperLogLogAggregation,
    InvertibleReduceAggregateFunction,
    MaxAggregation,
    MeanAggregation,
    MinAggregation,
    QuantileAggregation,
    ReduceAggregateFunction,
    CappedSessionWindow,
    GenericSessionWindow,
    SessionWindow,
    SlidingWindow,
    SumAggregation,
    TimeMeasure,
    TumblingWindow,
    Window,
    WindowMeasure,
    WindowOperator,
)
from .hybrid import HybridWindowOperator
from .simulator import SlicingWindowOperator
from .state import MemoryStateFactory, StateFactory

__version__ = "0.1.0"


def __getattr__(name):
    # heavy submodules load lazily so `import scotty_tpu` stays cheap and
    # jax-free until an operator is actually built.
    if name == "TpuWindowOperator":
        from .engine import TpuWindowOperator

        return TpuWindowOperator
    if name == "EngineConfig":
        from .engine import EngineConfig

        return EngineConfig
    if name == "KeyedTpuWindowOperator":
        from .parallel import KeyedTpuWindowOperator

        return KeyedTpuWindowOperator
    if name == "GlobalTpuWindowOperator":
        from .parallel import GlobalTpuWindowOperator

        return GlobalTpuWindowOperator
    if name == "StreamShaper":
        from .shaper import StreamShaper

        return StreamShaper
    if name == "ShaperConfig":
        from .shaper import ShaperConfig

        return ShaperConfig
    if name == "QueryService":
        from .serving import QueryService

        return QueryService
    if name == "QueryAdmission":
        from .serving import QueryAdmission

        return QueryAdmission
    if name == "LineRateFeed":
        from .ingest import LineRateFeed

        return LineRateFeed
    if name == "RingConfig":
        from .ingest import RingConfig

        return RingConfig
    if name == "SoakConfig":
        from .soak import SoakConfig

        return SoakConfig
    if name == "SoakRunner":
        from .soak import SoakRunner

        return SoakRunner
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")

__all__ = [
    "AggregateFunction", "AggregateWindow", "CountAggregation",
    "CountMinSketchAggregation",
    "DDSketchQuantileAggregation", "FixedBandWindow", "HyperLogLogAggregation",
    "InvertibleReduceAggregateFunction", "MaxAggregation", "MeanAggregation",
    "MinAggregation", "QuantileAggregation", "ReduceAggregateFunction",
    "CappedSessionWindow", "GenericSessionWindow", "SessionWindow", "SlidingWindow", "SumAggregation", "TimeMeasure",
    "TumblingWindow", "Window", "WindowMeasure", "WindowOperator",
    "SlicingWindowOperator", "MemoryStateFactory", "StateFactory",
    "HybridWindowOperator", "TpuWindowOperator", "EngineConfig",
    "KeyedTpuWindowOperator", "GlobalTpuWindowOperator",
    "StreamShaper", "ShaperConfig",
    "QueryService", "QueryAdmission",
    "LineRateFeed", "RingConfig", "SoakConfig", "SoakRunner",
]
