"""Hybrid operator: automatic device/host backend selection.

The reference picks its slice storage mode with a decision tree over the
registered workload (eager vs lazy, SliceFactory.java:17-28). The TPU
framework has the same shape of decision one level up: workloads whose
windows/aggregations have a device realization run on the TPU engine
(`scotty_tpu.engine.TpuWindowOperator`); everything else — count-measure
windows, session/context-aware windows, host-only holistic aggregates,
non-numeric elements — runs on the reference-semantics host operator
(`scotty_tpu.simulator.SlicingWindowOperator`). The decision is made lazily
at first element, once all windows/aggregations are registered (the same
point the reference instantiates its slice factory).
"""

from __future__ import annotations

from typing import Any, List, Optional

from .core.aggregates import AggregateFunction
from .core.operator import AggregateWindow, WindowOperator
from .core.windows import (
    FixedBandWindow,
    ForwardContextAware,
    ForwardContextFree,
    SlidingWindow,
    TumblingWindow,
    Window,
    WindowMeasure,
)
from .state import StateFactory


class HybridWindowOperator(WindowOperator):
    """WindowOperator that routes to the TPU engine when possible."""

    def __init__(self, state_factory: Optional[StateFactory] = None,
                 engine_config=None, force_backend: Optional[str] = None,
                 assume_inorder: Optional[bool] = None):
        self.state_factory = state_factory
        self.engine_config = engine_config
        self.force_backend = force_backend
        if assume_inorder is not None:
            # r1-r3 gated count+time mixes on this in-order declaration;
            # since r4 those mixes run on device in- and out-of-order, so
            # the flag no longer affects routing (VERDICT r4 weak #6 —
            # don't silently ignore a semantically loaded argument).
            import warnings

            warnings.warn(
                "HybridWindowOperator(assume_inorder=...) is deprecated "
                "and has no effect: count+time mixes run on the device "
                "engine for in- AND out-of-order streams since r4 "
                "(engine/operator._mixed_cut_calculus). Drop the argument.",
                DeprecationWarning, stacklevel=2)
        self.assume_inorder = bool(assume_inorder)
        self.windows: List[Window] = []
        self.aggregations: List[AggregateFunction] = []
        self.max_lateness = 1000
        self._delegate: Optional[WindowOperator] = None

    # -- decision tree (device analogue of SliceFactory.java:17-22) --------
    def _device_realizable(self) -> bool:
        from .core.windows import SessionWindow

        for w in self.windows:
            if isinstance(w, SessionWindow):
                # device sessions are fully general (bounded active-session
                # arrays, in- or out-of-order, any mix with time-grid
                # windows — engine/sessions.py); only the Count measure
                # stays host-only
                if w.measure != WindowMeasure.Time:
                    return False
                continue
            if isinstance(w, (ForwardContextAware, ForwardContextFree)):
                # user context windows: device when they provide the
                # device face (engine/context.py) AND are time-measured
                # (the device calculus runs over event timestamps; the
                # host face runs count contexts over arrival positions),
                # host otherwise
                if w.window_measure != WindowMeasure.Time:
                    return False
                if w.device_context_spec() is None:
                    return False
                continue
            if not isinstance(w, (TumblingWindow, SlidingWindow,
                                  FixedBandWindow)):
                return False
            if w.measure == WindowMeasure.Count \
                    and isinstance(w, FixedBandWindow):
                return False
        # count+time mixes run on device in- AND out-of-order since r4:
        # the reference's ripple (SliceManager.java:64-86) is realized as
        # record-buffer rank ranges + the arrival-order cut calculus
        # (engine/operator._mixed_cut_calculus), so no in-order declaration
        # is needed any more.
        for a in self.aggregations:
            if a.device_spec() is None:
                return False
        return bool(self.windows) and bool(self.aggregations)

    @property
    def backend(self) -> str:
        if self._delegate is None:
            return "undecided"
        from .engine import TpuWindowOperator

        return ("device" if isinstance(self._delegate, TpuWindowOperator)
                else "host")

    def _resolve(self) -> WindowOperator:
        if self._delegate is None:
            use_device = (self.force_backend == "device"
                          or (self.force_backend is None
                              and self._device_realizable()))
            if use_device:
                from .engine import TpuWindowOperator

                d = TpuWindowOperator(config=self.engine_config)
            else:
                from .simulator import SlicingWindowOperator

                d = SlicingWindowOperator(self.state_factory)
            for w in self.windows:
                d.add_window_assigner(w)
            for a in self.aggregations:
                d.add_aggregation(a)
            d.set_max_lateness(self.max_lateness)
            self._delegate = d
        return self._delegate

    # -- WindowOperator contract -------------------------------------------
    def process_element(self, element: Any, ts: int) -> None:
        self._resolve().process_element(element, ts)

    def process_elements(self, elements, timestamps) -> None:
        self._resolve().process_elements(elements, timestamps)

    def process_watermark(self, watermark_ts: int) -> List[AggregateWindow]:
        return self._resolve().process_watermark(watermark_ts)

    def add_window_assigner(self, window: Window) -> None:
        if self._delegate is not None:
            self._delegate.add_window_assigner(window)
        self.windows.append(window)

    def add_aggregation(self, window_function: AggregateFunction) -> None:
        if self._delegate is not None:
            self._delegate.add_aggregation(window_function)
        self.aggregations.append(window_function)

    def set_max_lateness(self, max_lateness: int) -> None:
        self.max_lateness = max_lateness
        if self._delegate is not None:
            self._delegate.set_max_lateness(max_lateness)
