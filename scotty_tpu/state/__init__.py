"""Pluggable state primitives (parity with the reference ``state/`` module,
SURVEY.md §2.3).

``StateFactory`` (state/.../StateFactory.java:5-12) creates three cell types:
``ValueState`` (ValueState.java:3-9), ``ListState`` (ListState.java:5-12) and
``SetState`` (SetState.java:3-15). The host-side operator keeps every slice
partial in a ``ValueState`` and every lazy slice's record buffer in a
``SetState``, exactly like the reference — this is the seam reserved for
checkpointable backends (README.md:66). The TPU engine does not use these
cells (its state is a device pytree checkpointed via orbax); they exist for
the host path and for API parity.

The in-memory ``SetState`` is *ordered and deduplicating on the sort key*,
mirroring the reference's ``TreeSet``-backed MemorySetState
(state/.../memory/MemorySetState.java:7-50): two records comparing equal
(same timestamp — StreamRecord.compareTo, slicing/.../StreamRecord.java:24-27)
collapse to one entry. That quirk is observable in lazy-slice repair and is
preserved deliberately.
"""

from __future__ import annotations

import bisect
from typing import Any, Generic, Iterable, Iterator, List, Optional, TypeVar

T = TypeVar("T")


class State:
    """Base state cell (state/.../State.java:5-10)."""

    def clean(self) -> None:
        raise NotImplementedError

    def is_empty(self) -> bool:
        raise NotImplementedError


class ValueState(State, Generic[T]):
    """Single-value cell (state/.../ValueState.java:3-9)."""

    def get(self) -> Optional[T]:
        raise NotImplementedError

    def set(self, value: T) -> None:
        raise NotImplementedError


class ListState(State, Generic[T]):
    """Indexed list cell (state/.../ListState.java:5-12)."""

    def get(self, index: int) -> T:
        raise NotImplementedError

    def append(self, value: T) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[T]:
        raise NotImplementedError


class SetState(State, Generic[T]):
    """Ordered set cell (state/.../SetState.java:3-15). The reference API
    spells ``dropFrist`` [sic]; we use ``drop_first``."""

    def get_first(self) -> T:
        raise NotImplementedError

    def get_last(self) -> T:
        raise NotImplementedError

    def drop_first(self) -> T:
        raise NotImplementedError

    def drop_last(self) -> T:
        raise NotImplementedError

    def add(self, value: T) -> None:
        raise NotImplementedError

    def __iter__(self) -> Iterator[T]:
        raise NotImplementedError


class StateFactory:
    """Creates the three cell types (state/.../StateFactory.java:5-12)."""

    def create_value_state(self) -> ValueState:
        raise NotImplementedError

    def create_list_state(self) -> ListState:
        raise NotImplementedError

    def create_set_state(self) -> SetState:
        raise NotImplementedError


# ---------------------------------------------------------------------------
# In-memory implementations (state/.../memory/)
# ---------------------------------------------------------------------------


class MemoryValueState(ValueState[T]):
    """Field-backed value cell (memory/MemoryValueState.java:7-50)."""

    __slots__ = ("_value",)

    def __init__(self):
        self._value: Optional[T] = None

    def get(self) -> Optional[T]:
        return self._value

    def set(self, value: T) -> None:
        self._value = value

    def clean(self) -> None:
        self._value = None

    def is_empty(self) -> bool:
        return self._value is None

    def __repr__(self) -> str:
        return f"MemoryValueState({self._value!r})"


class MemoryListState(ListState[T]):
    """List-backed cell (memory/MemoryListState.java:8-36)."""

    __slots__ = ("_values",)

    def __init__(self):
        self._values: List[T] = []

    def get(self, index: int) -> T:
        return self._values[index]

    def append(self, value: T) -> None:
        self._values.append(value)

    def clean(self) -> None:
        self._values.clear()

    def is_empty(self) -> bool:
        return not self._values

    def __iter__(self) -> Iterator[T]:
        return iter(self._values)


class MemorySetState(SetState[T]):
    """Ordered, key-deduplicating set cell — the Python analogue of the
    reference's TreeSet (memory/MemorySetState.java:7-50). Elements must be
    mutually comparable; an element comparing equal to an existing one is NOT
    inserted (TreeSet.add semantics)."""

    __slots__ = ("_values",)

    def __init__(self):
        self._values: List[T] = []

    def add(self, value: T) -> None:
        i = bisect.bisect_left(self._values, value)
        if i < len(self._values) and not (value < self._values[i] or self._values[i] < value):
            return  # compares equal → TreeSet drops it
        self._values.insert(i, value)

    def get_first(self) -> T:
        return self._values[0]

    def get_last(self) -> T:
        return self._values[-1]

    def drop_first(self) -> T:
        return self._values.pop(0)

    def drop_last(self) -> T:
        return self._values.pop()

    def clean(self) -> None:
        self._values.clear()

    def is_empty(self) -> bool:
        return not self._values

    def __iter__(self) -> Iterator[T]:
        return iter(self._values)

    def __len__(self) -> int:
        return len(self._values)


class MemoryStateFactory(StateFactory):
    """In-memory factory (memory/MemoryStateFactory.java:5-20)."""

    def create_value_state(self) -> MemoryValueState:
        return MemoryValueState()

    def create_list_state(self) -> MemoryListState:
        return MemoryListState()

    def create_set_state(self) -> MemorySetState:
        return MemorySetState()
