"""The epoch ledger: the sink-side half of a checkpoint transaction.

Emissions between supervisor checkpoints form an **epoch**. The ledger
is the tiny durable record — ``(epoch, committed_seq)`` — that rides
*inside* the checkpoint bundle (``ledger.json``, written through
:mod:`scotty_tpu.utils.fsio` so the bundle manifest covers it) and
therefore commits **atomically with** the engine state and the source
offset at the supervisor's single ``os.replace`` pointer flip: state,
offset and delivered-seq can never tear apart. A restore that picks any
lineage generation gets that generation's ledger with it, so the
:class:`~scotty_tpu.delivery.sink.TransactionalSink` always rewinds its
sequence numbering to exactly the head the restored state corresponds
to.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass
from typing import Optional

#: the ledger file inside a checkpoint bundle
LEDGER_NAME = "ledger.json"
LEDGER_SCHEMA = "scotty_tpu.delivery_ledger/1"


@dataclass
class EpochLedger:
    """``epoch`` — committed checkpoints so far (the epoch emissions
    after this checkpoint carry); ``committed_seq`` — the highest
    emission sequence number covered by the checkpoint (-1 before the
    first emission)."""

    epoch: int = 0
    committed_seq: int = -1

    def save(self, dir_path: str) -> None:
        """Write ``ledger.json`` into an open (pre-commit) checkpoint
        directory via the fault-injectable fsio layer — one more file in
        the bundle the manifest digests; the atomicity comes from the
        bundle's own commit, not from this write."""
        from ..utils import fsio

        doc = {"schema": LEDGER_SCHEMA, "epoch": int(self.epoch),
               "committed_seq": int(self.committed_seq)}
        fsio.write_bytes(os.path.join(dir_path, LEDGER_NAME),
                         json.dumps(doc).encode())

    @staticmethod
    def load(dir_path: str) -> Optional["EpochLedger"]:
        """The ledger committed with a checkpoint, or None for bundles
        from before the delivery layer (or non-sink runs) — the caller
        then starts from genesis."""
        path = os.path.join(dir_path, LEDGER_NAME)
        if not os.path.exists(path):
            return None
        with open(path) as f:
            doc = json.load(f)
        if not str(doc.get("schema", "")).startswith(
                "scotty_tpu.delivery_ledger/"):
            raise ValueError(
                f"{path}: not a delivery ledger "
                f"(schema={doc.get('schema')!r})")
        return EpochLedger(epoch=int(doc["epoch"]),
                           committed_seq=int(doc["committed_seq"]))
