"""Transactional sinks: the exactly-once output boundary.

The reference Scotty inherits exactly-once from its host engines (Flink
barrier snapshots + two-phase-commit sinks); scotty_tpu is its own
engine, and before this layer a supervised restore replayed every
emission since the last checkpoint straight into the sink — silent
duplicates on every recovery. :class:`TransactionalSink` closes that
gap with the epoch-ledger discipline:

* every emission is sequence-numbered ``(epoch, seq)`` — ``seq`` is a
  global monotonic counter, ``epoch`` the number of committed
  checkpoints at emission time; both are pure functions of stream
  position, so a deterministic replay regenerates identical tags;
* the ledger head commits **atomically with** the supervisor checkpoint
  (``ledger.json`` inside the bundle, one ``os.replace`` commit point —
  see :mod:`.ledger`);
* after a supervised restore the sink rewinds ``seq`` to the restored
  ledger's head; replayed emissions with ``seq <= delivered`` are
  suppressed exactly (counted ``delivery_duplicates_suppressed``,
  flight-recorded), so the downstream consumer observes each window
  result exactly once across any crash/restart sequence — including a
  lineage fallback to an older checkpoint, which just replays (and
  suppresses) more.

``at_least_once`` stays the default fast path: no suppression, no
bookkeeping beyond the counters, and nothing in the jitted engine is
touched either way (the sink is a pure host-side boundary).

The suppression horizon is the **in-process delivered high-water**: the
sink object outlives supervised restarts (it belongs to the driver, not
the crashed target generation). Across a full *process* restart the
horizon degrades to the restored ledger's committed head — emissions
delivered after the last checkpoint are then re-delivered, the honest
at-least-once limit of any sink without a two-phase-commit downstream
(document the contract, don't pretend past it).
"""

from __future__ import annotations

from typing import Callable, Optional

from .. import obs as _obs
from ..obs import flight as _flight
from .ledger import EpochLedger

#: delivery guarantee modes
AT_LEAST_ONCE = "at_least_once"
EXACTLY_ONCE = "exactly_once"
_MODES = (AT_LEAST_ONCE, EXACTLY_ONCE)


class TransactionalSink:
    """Wrap a downstream consumer with (epoch, seq) sequencing and
    replay suppression (module docstring).

    ``deliver(item, epoch, seq)`` is the downstream consumer; when None,
    :meth:`emit` just returns the deliver/suppress verdict and the
    caller (a run loop) yields the item itself — both faces are used by
    the connector run loops and the soak harness.
    """

    def __init__(self, deliver: Optional[Callable] = None,
                 mode: str = AT_LEAST_ONCE, obs=None):
        if mode not in _MODES:
            raise ValueError(
                f"delivery mode must be one of {_MODES}, got {mode!r}")
        self.deliver = deliver
        self.mode = mode
        self.obs = obs
        self.epoch = 0                 # committed checkpoints so far
        self.next_seq = 0              # seq the next emission gets
        self.delivered = -1            # high-water actually handed down
        self.emitted = 0               # deliveries (post-suppression)
        self.suppressed = 0            # exact duplicate count

    # -- the emission path -------------------------------------------------
    def emit(self, item) -> bool:
        """Sequence one emission; returns True when it was (or should
        be) delivered downstream, False when it was suppressed as a
        replayed duplicate."""
        seq = self.next_seq
        self.next_seq = seq + 1
        if self.mode == EXACTLY_ONCE and seq <= self.delivered:
            self.suppressed += 1
            if self.obs is not None:
                self.obs.counter(
                    _obs.DELIVERY_DUPLICATES_SUPPRESSED).inc()
                self.obs.flight_event(_flight.DUPLICATE_SUPPRESSED,
                                      "sink", float(seq))
            return False
        if self.obs is not None:
            # the per-emission flight event IS an enumerable crash site.
            # It MUST fire BEFORE the downstream handoff and before the
            # delivered high-water advances: a crash here then models
            # "died at the emission flush" and the replay re-emits this
            # seq — the consumer still sees it exactly once. (Fired
            # after the mark, a crash here would mark an item delivered
            # that no consumer ever received, and the replay would
            # suppress it — a silent loss the crash-point sweep caught.)
            self.obs.flight_event(_flight.EMIT, "sink", float(seq))
        if self.deliver is not None:
            self.deliver(item, self.epoch, seq)
        self.delivered = max(self.delivered, seq)
        self.emitted += 1
        if self.obs is not None:
            self.obs.counter(_obs.DELIVERY_EMITTED).inc()
            if self.obs.latency is not None:
                # emission-latency sink stamp (ISSUE 14): the first
                # delivery of the awaiting chain's batch sets the
                # first-emit endpoint, every delivery advances the
                # whole-emission lag. AFTER the high-water mark on
                # purpose — a stamp must never become a new crash
                # site between sequencing and delivery.
                self.obs.latency.sink_delivered()
            slo = getattr(self.obs, "slo", None)
            if slo is not None:
                # SLO delivery stamp (ISSUE 19): same AFTER-the-high-
                # water placement as the latency stamp above — a
                # delivered-count tick must never become a new crash
                # site inside the exactly-once emission path
                slo.sink_delivered()
        return True

    def filter(self, items):
        """List-face of :meth:`emit`: the subset of ``items`` to hand
        downstream, in order. Crash caveat: a crash inside :meth:`emit`
        discards the whole return value — under supervision use
        :meth:`drain_into` (or per-item :meth:`emit`) so items already
        sequenced reach the collector before the next one can crash."""
        return [it for it in items if self.emit(it)]

    def drain_into(self, items, collect: Callable) -> None:
        """Crash-safe batch handoff: each item that passes :meth:`emit`
        reaches ``collect`` BEFORE the next emission (whose flight
        event is an enumerable crash site) can raise — so a mid-batch
        crash replays only the items the collector never received."""
        for it in items:
            if self.emit(it):
                collect(it)

    # -- the checkpoint transaction ----------------------------------------
    def save(self, dir_path: str) -> None:
        """Write the ledger head into an open (pre-commit) checkpoint
        bundle: ``committed_seq`` = everything emitted so far,
        ``epoch`` = the epoch that begins when this checkpoint commits —
        which is exactly the epoch a restore from this bundle resumes
        in, keeping (epoch, seq) tags replay-stable."""
        EpochLedger(epoch=self.epoch + 1,
                    committed_seq=self.next_seq - 1).save(dir_path)

    def on_commit(self, pos: int) -> None:
        """The checkpoint's pointer flip succeeded: the epoch closes."""
        self.epoch += 1
        if self.obs is not None:
            self.obs.counter(_obs.DELIVERY_EPOCHS_COMMITTED).inc()
            self.obs.flight_event(_flight.EPOCH_COMMIT, "sink",
                                  float(self.epoch))

    def restore(self, ckpt_dir: Optional[str]) -> None:
        """Rewind to a restored checkpoint's ledger (or to genesis when
        the supervisor restarts with no checkpoint yet). The delivered
        high-water is deliberately NOT rewound — it is the suppression
        horizon that keeps the replay exactly-once."""
        ledger = EpochLedger.load(ckpt_dir) if ckpt_dir is not None \
            else None
        if ledger is None:
            self.epoch = 0
            self.next_seq = 0
        else:
            self.epoch = ledger.epoch
            self.next_seq = ledger.committed_seq + 1

    # -- telemetry ---------------------------------------------------------
    def snapshot(self) -> dict:
        return {"mode": self.mode, "epoch": self.epoch,
                "next_seq": self.next_seq, "delivered": self.delivered,
                "emitted": self.emitted, "suppressed": self.suppressed}
