"""Supervised exactly-once connector loops.

The PR 3 Supervisor knows how to restart fused pipelines and engine
operators; the connector run loops (iterable/kafka/asyncio) were outside
its reach — and outside any delivery guarantee. :func:`run_supervised`
closes the loop for any **replayable indexable record source**: drive a
run loop segment-at-a-time, committing the connector operator's state,
the source offset and the :class:`~scotty_tpu.delivery.sink.
TransactionalSink`'s ledger as ONE atomic checkpoint (the control-path
commands the run loops already support fire the commits at exact record
counts), and on any failure restore the newest verifying lineage
generation, rewind the source to its offset, and replay — the sink's
suppression horizon turns the at-least-once replay into an exactly-once
delivery stream.

``run_segment`` adapts the concrete loop; :func:`iterable_segment`,
:func:`kafka_segment` and :func:`asyncio_segment` cover the three
shipped run loops (the crash-point sweep drives all of them).
"""

from __future__ import annotations

from typing import Callable, List, Optional, Sequence

from ..resilience.supervisor import Supervisor, SupervisorGaveUp
from .sink import TransactionalSink


def _commit_schedule(supervisor: Supervisor, offset: int, total: int,
                     checkpoint_every: int):
    """Control rows committing a checkpoint at every absolute position
    ``k*checkpoint_every`` past ``offset`` (run-loop control counts are
    relative to the segment start)."""
    rows = []
    for pos in range(checkpoint_every, total + 1, checkpoint_every):
        if pos <= offset:
            continue

        def command(op, _pos=pos):
            supervisor.commit_checkpoint(
                _pos, lambda d: op.save(d), offset=_pos)

        rows.append((pos - offset, command))
    return rows


def iterable_segment(keyed: bool = True) -> Callable:
    """Segment runner over :func:`scotty_tpu.connectors.iterable.
    run_keyed` / ``run_global``."""
    from ..connectors import iterable as _iterable

    def segment(op, records, control, sink, collect):
        loop = _iterable.run_keyed if keyed else _iterable.run_global
        for item in loop(records, op, control=control, sink=sink):
            collect(item)

    return segment


def kafka_segment(deserialize: Optional[Callable] = None) -> Callable:
    """Segment runner over :class:`scotty_tpu.connectors.kafka.
    KafkaScottyWindowOperator.run` (records need key/value/timestamp)."""

    def segment(op, records, control, sink, collect):
        from ..connectors.kafka import (KafkaScottyWindowOperator,
                                        _default_deserialize)

        kafka = KafkaScottyWindowOperator(
            operator=op,
            deserialize=deserialize or _default_deserialize)
        kafka.run(records, on_result=collect, control=control, sink=sink)

    return segment


def asyncio_segment() -> Callable:
    """Segment runner over :func:`scotty_tpu.connectors.
    asyncio_connector.run_keyed_async` (one fresh event loop per
    segment — a crashed segment's loop dies with it)."""

    def segment(op, records, control, sink, collect):
        import asyncio

        from ..connectors.asyncio_connector import run_keyed_async

        async def _source():
            for rec in records:
                yield rec

        async def _run():
            await run_keyed_async(_source(), op, emit=collect,
                                  control=control, sink=sink)

        asyncio.run(_run())

    return segment


def run_supervised(records: Sequence, make_operator: Callable,
                   supervisor: Supervisor,
                   sink: Optional[TransactionalSink] = None,
                   checkpoint_every: int = 64,
                   run_segment: Optional[Callable] = None,
                   final_watermark: Optional[int] = None) -> List:
    """Drive a connector run loop over ``records`` under supervision
    with transactional delivery (module docstring); returns every item
    actually delivered downstream, across all restarts — the consumer's
    exact view of the stream.

    ``make_operator()`` builds a fresh connector operator exposing the
    PR 3 ``save(dir)``/``restore(dir)`` face; ``records`` must be
    indexable and replayable (the source-offset contract). A final
    checkpoint commits at end-of-stream so a post-run restart replays
    nothing.
    """
    if run_segment is None:
        run_segment = iterable_segment(keyed=True)
    sink = sink or TransactionalSink()
    if supervisor.sink is None:
        supervisor.sink = sink
    delivered: List = []
    total = len(records)
    while True:
        op = make_operator()
        ckpt = supervisor.latest_checkpoint()
        offset = 0
        if ckpt is not None:
            d, offset = ckpt
            op.restore(d)
            sink.restore(d)
        else:
            sink.restore(None)
        try:
            control = _commit_schedule(supervisor, offset, total,
                                       checkpoint_every)
            run_segment(op, records[offset:], control, sink,
                        delivered.append)
            if final_watermark is not None:
                # per-item handoff: a crash mid-flush must not discard
                # emissions already sequenced (the batch face would)
                sink.drain_into(op.process_watermark(final_watermark),
                                delivered.append)
            # the closing commit covers the final-watermark emissions
            # too, so a post-run restart replays nothing
            supervisor.commit_checkpoint(
                total, lambda d: op.save(d), offset=total)
            return delivered
        except SupervisorGaveUp:
            raise
        except Exception as e:        # noqa: BLE001 — supervised edge
            supervisor.handle_failure(e)   # raises SupervisorGaveUp at budget
