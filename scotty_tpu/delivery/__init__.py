"""Exactly-once delivery (ISSUE 8 tentpole): epoch ledger +
transactional sinks + supervised connector loops.

See :mod:`.sink` for the delivery contract, :mod:`.ledger` for the
atomic checkpoint transaction, and :mod:`.runner` for the supervised
run-loop face. ``at_least_once`` stays the default everywhere; pass a
:class:`TransactionalSink` in ``exactly_once`` mode to the connector
run loops / the Supervisor / the soak harness to arm suppression.
"""

from .ledger import LEDGER_NAME, EpochLedger
from .runner import (
    asyncio_segment,
    iterable_segment,
    kafka_segment,
    run_supervised,
)
from .sink import AT_LEAST_ONCE, EXACTLY_ONCE, TransactionalSink

__all__ = [
    "AT_LEAST_ONCE", "EXACTLY_ONCE", "TransactionalSink",
    "EpochLedger", "LEDGER_NAME",
    "run_supervised", "iterable_segment", "kafka_segment",
    "asyncio_segment",
]
