"""Benchmark harness (reference benchmark/ module parity, SURVEY.md §2.5)."""

from .harness import (
    BenchmarkConfig,
    BenchResult,
    ThroughputStatistics,
    generate_batches,
    make_aggregation,
    parse_window_spec,
    run_benchmark,
)
from .runner import load_config, main, run_cell, run_config

__all__ = [
    "BenchmarkConfig", "BenchResult", "ThroughputStatistics",
    "generate_batches", "load_config", "main", "make_aggregation",
    "parse_window_spec", "run_benchmark", "run_cell", "run_config",
]
