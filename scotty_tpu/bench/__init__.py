"""Benchmark harness (reference benchmark/ module parity, SURVEY.md §2.5)."""

from .harness import (
    BenchmarkConfig,
    BenchResult,
    ThroughputStatistics,
    generate_batches,
    make_aggregation,
    parse_window_spec,
    run_benchmark,
)

__all__ = [
    "BenchmarkConfig", "BenchResult", "ThroughputStatistics",
    "generate_batches", "make_aggregation", "parse_window_spec",
    "run_benchmark",
]
