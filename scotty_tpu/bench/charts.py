"""Benchmark chart generation — parity with the reference's README figures
(charts/SlidingWindow.png, charts/ConcurrentTumblingWindows.png;
README.md:47-58). Reads bench_results/result_*.json written by
``python -m scotty_tpu.bench`` and writes charts/*.png.

Run: ``python -m scotty_tpu.bench.charts``.

Colors are the first two categorical slots of a validated palette (blue
#2a78d6, orange #eb6834 — adjacent-pair CVD-safe per the palette's
validation record); text wears ink tokens, series identity is carried by
the legend + a direct label per line.
"""

from __future__ import annotations

import json
import os

BLUE, ORANGE = "#2a78d6", "#eb6834"
INK, MUTED, GRID = "#1a1a19", "#6b6a62", "#e5e4dc"


def _style(ax, title, xlabel):
    ax.set_title(title, color=INK, fontsize=11, loc="left", pad=12)
    ax.set_xlabel(xlabel, color=MUTED, fontsize=9)
    ax.set_ylabel("tuples / s (log)", color=MUTED, fontsize=9)
    ax.set_yscale("log")
    ax.grid(True, axis="y", color=GRID, linewidth=0.8)
    for s in ("top", "right"):
        ax.spines[s].set_visible(False)
    for s in ("left", "bottom"):
        ax.spines[s].set_color(GRID)
    ax.tick_params(colors=MUTED, labelsize=8)


def _series(rows, engine):
    return [r for r in rows if r.get("engine") == engine
            and "error" not in r]


def _draw(plt, path, title, xlabel, xticklabels, get):
    fig, ax = plt.subplots(figsize=(7.2, 4.2), dpi=150)
    x = list(range(len(xticklabels)))
    for eng, color, name in [
            ("TpuEngine", BLUE, "scotty_tpu (slicing)"),
            ("Buckets", ORANGE, "bucket baseline (no sharing)")]:
        y = get(eng)
        ax.plot(x, y, color=color, linewidth=2, marker="o", markersize=5,
                label=name)
        ax.annotate(name, (x[0], y[0]), textcoords="offset points",
                    xytext=(2, 10), ha="left", color=INK, fontsize=8.5)
    ax.set_xticks(x, xticklabels)
    _style(ax, title, xlabel)
    ax.legend(frameon=False, fontsize=8, labelcolor=INK, loc="center right")
    fig.tight_layout()
    fig.savefig(path)


def main(results_dir: str = "bench_results", out_dir: str = "charts",
         echo=None) -> int:
    from ..utils import stdout_echo

    if echo is None:
        echo = stdout_echo
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    os.makedirs(out_dir, exist_ok=True)

    rows = json.load(open(os.path.join(results_dir,
                                       "result_sliding-suite.json")))
    slides = [60000, 10000, 1000, 500, 250, 100, 1]

    def tps_sliding(eng):
        out = []
        for sl in slides:
            m = [r for r in _series(rows, eng)
                 if r["windows"] == f"Sliding(60000,{sl})"]
            out.append(m[-1]["tuples_per_sec"] if m else None)
        return out

    _draw(plt, os.path.join(out_dir, "sliding_suite.png"),
          "Sliding 60 s window, slide 60 s → 1 ms "
          "(≤ 60k concurrent windows), v5e-1",
          "slide",
          ["60 s", "10 s", "1 s", "500 ms", "250 ms", "100 ms", "1 ms"],
          tps_sliding)

    rows2 = json.load(open(os.path.join(results_dir,
                                        "result_random-tumbling.json")))
    ns = [1, 10, 100, 1000]

    def tps_tumbling(eng):
        out = []
        for n in ns:
            m = [r for r in _series(rows2, eng)
                 if r["windows"] == f"randomTumbling({n},1000,20000)"]
            out.append(m[-1]["tuples_per_sec"] if m else None)
        return out

    _draw(plt, os.path.join(out_dir, "concurrent_tumbling.png"),
          "Concurrent random tumbling windows (1 → 1000), v5e-1",
          "# concurrent windows", [str(n) for n in ns], tps_tumbling)
    echo(f"-> {out_dir}/sliding_suite.png, "
         f"{out_dir}/concurrent_tumbling.png")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
